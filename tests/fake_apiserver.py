"""In-process fake kube-apiserver (SURVEY.md §4: the fake layer the
reference lacks).

Speaks the small REST subset the tpu-operator and `tpuctl apply` use:

  GET    <collection>/<name>   -> 200 stored object | 404
  POST   <collection>          -> 201, stores body at collection/<name>
  PUT    <collection>/<name>   -> 200, replaces
  PATCH  <collection>/<name>   -> 200, merge-patch (RFC 7386: null deletes)
  PATCH  <collection>/<name>?fieldManager=M[&force=true]
         with application/apply-patch+yaml -> server-side apply (KEP-555):
         per-field managedFields ownership, dropped-field pruning, 409
         Conflict naming the competing manager (see _serve_ssa)
  DELETE <collection>/<name>   -> 200 | 404

The store is a flat {path: object} dict — the path grammar
(/api/v1/... vs /apis/<group>/...) is produced by the client side, the fake
only needs prefix bookkeeping. ``auto_ready`` fills workload status at create
time (DaemonSet desired==ready etc.) so convergence tests don't need a node
simulator; gating tests leave it off and flip readiness by hand via
``set_ready``. Every request is appended to ``log`` for ordering assertions.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs


def prom_escape(value: str) -> str:
    """Prometheus label-value escaping (backslash, double quote,
    newline) — the exposition format requires it, and the audit's path
    labels are client-controlled bytes. Twin of telemetry._escape and
    the C++ promescape.h helper."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def parse_traceparent(header: str) -> Tuple[str, str]:
    """``(trace_id, parent_id)`` from a W3C traceparent header —
    ``("", "")`` for absent/malformed input (a server must tolerate
    garbage headers). Kept dependency-free like the rest of this fake
    (no tpu_cluster import), shape-pinned against
    telemetry.parse_traceparent by tests/test_trace_correlation.py."""
    parts = (header or "").strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return "", ""
    hexdigits = set("0123456789abcdefABCDEF")
    for field in (parts[1], parts[2]):
        # strict digit check, like the C++ twin: int(x, 16) would accept
        # '0x' prefixes / signs / whitespace the other parsers reject
        if not set(field) <= hexdigits or set(field) == {"0"}:
            return "", ""
    return parts[1], parts[2]


def merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386 JSON merge patch."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k), v)
    return out


# --------------------------------------------------------------- server-side
# apply (KEP-555). The fake implements the real mechanism at the granularity
# the clients rely on: per-field ownership tracked per fieldManager in
# metadata.managedFields, apply-merge that prunes fields a manager owned
# before but dropped from its new intent, and 409 Conflict (naming the
# competing manager) when an apply would change a field another manager
# owns, unless ?force=true takes it over. Simplification, documented and
# mirrored by the Python twin (kubeapply._fields_v1): arrays are ATOMIC
# leaves (x-kubernetes-list-type: atomic semantics) — no k:/v: list-member
# keys — which is exactly how merge-patch already treated them here.

def field_set(obj: Any) -> Dict[str, Any]:
    """fieldsV1-style ownership descriptor for one applied intent: nested
    ``{"f:<key>": {...}}`` dicts mirroring the object's dict structure;
    scalars, arrays and nulls are leaves (``{}``). Twin of
    ``kubeapply._fields_v1`` (parity-pinned by tests/test_pipeline.py)."""
    out: Dict[str, Any] = {}
    if not isinstance(obj, dict):
        return out
    for k, v in obj.items():
        out[f"f:{k}"] = field_set(v) if isinstance(v, dict) else {}
    return out


def _leaf_paths(fields: Dict[str, Any], prefix=()) -> set:
    """fieldsV1 nested dict -> set of owned leaf paths (tuples of keys)."""
    paths = set()
    for k, v in fields.items():
        key = prefix + (k[2:],)  # strip the "f:" marker
        if v:
            paths |= _leaf_paths(v, key)
        else:
            paths.add(key)
    return paths


def _paths_to_fields(paths) -> Dict[str, Any]:
    """Inverse of :func:`_leaf_paths` (canonical nested fieldsV1 form)."""
    out: Dict[str, Any] = {}
    for path in sorted(paths):
        node = out
        for k in path:
            node = node.setdefault(f"f:{k}", {})
    return out


_MISSING = object()


def _value_at(obj: Any, path) -> Any:
    for k in path:
        if not isinstance(obj, dict) or k not in obj:
            return _MISSING
        obj = obj[k]
    return obj


def _delete_at(obj: Any, path) -> None:
    """Remove the value at ``path``, dropping dict parents it empties."""
    if not path:
        return
    parents = []
    node = obj
    for k in path[:-1]:
        if not isinstance(node, dict) or k not in node:
            return
        parents.append((node, k))
        node = node[k]
    if isinstance(node, dict):
        node.pop(path[-1], None)
    for parent, key in reversed(parents):
        child = parent.get(key)
        if isinstance(child, dict) and not child:
            del parent[key]


def ssa_merge(target: Any, intent: Any) -> Any:
    """Apply-merge: dicts merge per key, everything else (scalars, arrays,
    nulls) replaces wholesale. Unlike RFC 7386 there is NO null-deletes
    rule — removal happens through ownership pruning, not the payload."""
    if not isinstance(intent, dict):
        return intent
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in intent.items():
        out[k] = ssa_merge(out.get(k), v)
    return out


# Kinds whose metadata.generation tracks spec changes. TpuStackPolicy is
# the operator's CR (status subresource declared in its CRD), so spec edits
# bump generation exactly like the workload kinds.
GENERATION_KINDS = ("DaemonSet", "Deployment", "TpuStackPolicy")

# Path segments treated as collections for list-style GETs (mirrors the
# plurals the clients construct paths from). A GET whose last segment is
# anything else is an object GET and 404s when absent.
COLLECTION_SEGMENTS = frozenset({
    "namespaces", "configmaps", "secrets", "services", "serviceaccounts",
    "pods", "events", "daemonsets", "deployments", "statefulsets", "jobs",
    "clusterroles", "clusterrolebindings", "roles", "rolebindings",
    "customresourcedefinitions", "tpustackpolicies", "nodes", "leases",
})


def ready_status(obj: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    kind = obj.get("kind")
    gen = obj.get("metadata", {}).get("generation", 1)
    if kind == "DaemonSet":
        return {"desiredNumberScheduled": 2, "numberReady": 2,
                "updatedNumberScheduled": 2, "observedGeneration": gen}
    if kind == "Deployment":
        want = obj.get("spec", {}).get("replicas", 1)
        return {"readyReplicas": want, "availableReplicas": want,
                "updatedReplicas": want, "observedGeneration": gen}
    if kind == "Job":
        return {"succeeded": obj.get("spec", {}).get("completions", 1)}
    if kind == "CustomResourceDefinition":
        # real apiservers establish a valid CRD within moments; the apply
        # backends gate CR creation on this condition
        return {"conditions": [{"type": "Established", "status": "True"}]}
    return None


def _filter_selector(items, query: str):
    """Apply a ?labelSelector= from a collection GET: exact `k=v` matches
    and bare-key existence (`k`), comma-separated."""
    from urllib.parse import parse_qs

    sel = parse_qs(query).get("labelSelector", [""])[0]
    if not sel:
        return items
    terms = [t.split("=", 1) if "=" in t else [t, None]
             for t in sel.split(",") if t]
    out = []
    for obj in items:
        labels = obj.get("metadata", {}).get("labels", {})
        if all(labels.get(k) == v if v is not None else k in labels
               for k, v in terms):
            out.append(obj)
    return out


# Timer-driven node-lifecycle fault kinds (ISSUE 10; cordon pair added
# by ISSUE 18) — the chaos-script spellings of the FakeApiServer node
# hooks below.
_NODE_FAULT_KINDS = ("node_not_ready", "node_ready", "evict_pods",
                     "cordon_node", "uncordon_node")


# ------------------------------------------------------------------ fleet
# (ISSUE 11): the synthetic 500-1000 node cluster the fleet-scale work
# runs against. Kept dependency-free like the rest of this fake (no
# tpu_cluster import) — the label/capacity spellings are twins of
# admission.node_manifest and are pinned by tests/test_fleet.py.

FLEET_ACCELERATOR_LABEL = "google.com/tpu.accelerator-type"
FLEET_TPU_RESOURCE = "google.com/tpu"
# twin of tpu_cluster/maintenance.py VERSION_LABEL (pinned by
# tests/test_maintenance.py) — the label set_node_version rewrites
FLEET_VERSION_LABEL = "tpu-stack.dev/stack-version"


def fleet_node(name: str, accelerator: str = "v5e-8", chips: int = 8,
               ready: bool = True) -> Dict[str, Any]:
    """One synthetic Node the way the feature-discovery + kubelet pair
    would publish it: discovery labels, TPU capacity, Ready condition,
    and kubelet-shaped nodeInfo/addresses status."""
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                FLEET_ACCELERATOR_LABEL: accelerator,
                "google.com/tpu.present": "true",
                "kubernetes.io/hostname": name,
            },
        },
        "status": {
            "capacity": {FLEET_TPU_RESOURCE: str(chips),
                         "cpu": "96", "memory": "384Gi"},
            "allocatable": {FLEET_TPU_RESOURCE: str(chips)},
            "conditions": [
                {"type": "Ready",
                 "status": "True" if ready else "False"},
            ],
            "nodeInfo": {"kubeletVersion": "v1.29.0",
                         "containerRuntimeVersion": "containerd://1.7.0",
                         "osImage": "Fake Linux"},
            "addresses": [{"type": "Hostname", "address": name}],
        },
    }


def fleet_store(num_nodes: int, accelerator: str = "v5e-8",
                chips_per_node: int = 8, pods_per_node: int = 1,
                namespace: str = "tpu-system",
                name_prefix: str = "fleet") -> Dict[str, Dict[str, Any]]:
    """A ``FakeApiServer(store=...)`` seed for a synthetic fleet:
    ``num_nodes`` Ready Nodes (discovery labels + TPU capacity + kubelet-
    shaped status) with ``pods_per_node`` running Pods bound to each via
    ``spec.nodeName`` — the object-count scale the sublinear pins run
    against without paying one HTTP request per seeded object."""
    store: Dict[str, Dict[str, Any]] = {}
    for i in range(num_nodes):
        node = f"{name_prefix}-{i:04d}"
        store[f"/api/v1/nodes/{node}"] = fleet_node(
            node, accelerator=accelerator, chips=chips_per_node)
        for p in range(pods_per_node):
            pod = f"{node}-pod-{p}"
            store[f"/api/v1/namespaces/{namespace}/pods/{pod}"] = {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": pod, "namespace": namespace,
                             "labels": {"app.kubernetes.io/part-of":
                                        "tpu-stack-fleet"}},
                "spec": {"nodeName": node,
                         "containers": [{"name": "w",
                                         "image": "tpu-stack/worker:v1"}]},
                "status": {"phase": "Running"},
            }
    return store


class ChaosEngine:
    """Scripted fault injection for the fake apiserver — the promotion of
    the old ad-hoc ``reject_posts``/``reject_watch`` hooks (which are now
    translated into chaos faults at construction) into one timed,
    composable fault machine.

    A script is a list of fault dicts; per request, faults are evaluated
    in script order and the first active match consumes it:

      {"status": 503, "for": 0.3}                       # every matching
                                                        # request 503s for
                                                        # 0.3s from "at"
      {"status": 429, "count": 3, "retry_after": 0.05}  # next 3 matching
                                                        # requests 429 with
                                                        # a Retry-After
      {"drop": 2}                                       # next 2 matching
                                                        # connections closed
                                                        # without any reply
      {"flap": True, "at": 0.5}                         # apiserver restart:
                                                        # watch history
                                                        # compacts, streams
                                                        # are 410-invalidated
                                                        # (FakeApiServer.flap)

    NODE-LIFECYCLE faults (ISSUE 10) — the failure-domain events the
    gang-admission loop must recover from; timer-driven like flap, fired
    once at ``at`` and recorded with their kind string:

      {"node_not_ready": "node-a", "at": 1.0}  # flip the Node's Ready
                                               # condition False
                                               # (FakeApiServer
                                               # .set_node_ready)
      {"node_ready": "node-a", "at": 2.0}      # ...and back — the
                                               # recovery half of a
                                               # drain/re-admit script
      {"evict_pods": "node-a", "at": 1.1}      # delete every Pod bound
                                               # to the node (spec
                                               # .nodeName), emitting
                                               # watch DELETED events —
                                               # what the eviction API
                                               # does to a drained node
      {"cordon_node": "node-a", "at": 1.2}     # set spec.unschedulable
                                               # (FakeApiServer
                                               # .set_node_unschedulable)
                                               # — a surprise cordon the
                                               # maintenance loop must
                                               # not fight or seat onto
      {"uncordon_node": "node-a", "at": 2.2}   # ...and clear it — the
                                               # recovery half

    SLOW-PATH faults (ISSUE 9) — the server that is slow rather than
    failing fast; all four honor ``for``/``count`` like status faults:

      {"stall": 2.0}            # accept the request, send NOTHING for
                                # 2 s, then sever the connection — only
                                # a whole-attempt wall deadline (never a
                                # per-socket-op timeout on a silent
                                # socket longer than the stall) gets the
                                # client unstuck
      {"trickle": 30}           # 200 + full headers at once, then the
                                # body dribbled at 30 bytes/second —
                                # DEFEATS per-socket-op timeouts by
                                # design (every recv succeeds); "body"
                                # overrides the dribbled JSON document
      {"truncate": True}        # 200 + Transfer-Encoding: chunked that
                                # promises more bytes than it sends and
                                # EOFs mid-chunk — mid-body for plain
                                # requests, mid-event for watch streams
      {"garbage": True}         # 200 whose body is half-JSON — a
                                # healthy-looking reply the client must
                                # classify as transport garbage, not
                                # parse; "body" (a raw string) overrides

    Optional keys on any fault: ``at`` (seconds after start(), default 0),
    ``match`` (path substring; ``exact: True`` for equality), ``method``
    (exact HTTP method), ``watch`` (True = only ``?watch=1`` GETs),
    ``ssa`` (True = only ``application/apply-patch+yaml`` PATCHes — the
    server-side-apply requests), ``body`` (override the injected Status
    body), ``retry_after`` (seconds, emitted as a Retry-After header —
    fractional allowed so tests stay fast; real servers send integers).
    A status fault with neither ``for`` nor ``count`` fires on every
    match until clear(). Every fired fault is recorded in ``fired`` for
    assertions."""

    def __init__(self, script):
        self._lock = threading.Lock()
        self._faults = [dict(f) for f in script]  # guarded-by: _lock
        self._t0: Optional[float] = None  # guarded-by: _lock
        # armed/cancelled only by the controlling thread (the server's
        # start/stop and test hooks); the timer threads never touch it
        self._timers: List[threading.Timer] = []  # thread-owned
        # (status|'drop', method, path), appended per fired fault
        self.fired: List[Tuple[Any, str, str]] = []  # guarded-by: _lock

    def start(self, server: "FakeApiServer") -> None:
        """Arm the script: the clock starts now, and flap faults schedule
        their restart timers against ``server``."""
        with self._lock:
            self._t0 = time.monotonic()
            faults = list(self._faults)
        for f in faults:
            if f.get("flap"):
                t = threading.Timer(max(0.0, f.get("at", 0.0)), server.flap)
                t.daemon = True
                t.start()
                self._timers.append(t)
                continue
            kind = next((k for k in _NODE_FAULT_KINDS if k in f), None)
            if kind is not None:
                t = threading.Timer(
                    max(0.0, f.get("at", 0.0)), self._fire_node_fault,
                    args=(server, kind, str(f[kind])))
                t.daemon = True
                t.start()
                self._timers.append(t)

    def stop(self) -> None:
        for t in self._timers:
            t.cancel()
        self._timers = []

    def clear(self) -> None:
        """End the chaos: pending faults (and un-fired flap timers) are
        dropped — the 'apiserver recovered' test hook."""
        self.stop()
        with self._lock:
            self._faults = []

    def fired_snapshot(self) -> List[Tuple[Any, str, str]]:
        """Copy of ``fired`` taken under the engine's lock — handler
        threads append concurrently while /__fake_metrics renders."""
        with self._lock:
            return list(self.fired)

    def _fire_node_fault(self, server: "FakeApiServer", kind: str,
                         node: str) -> None:
        """Timer body of one node-lifecycle fault: apply the lifecycle
        hook to ``server`` and record the firing under the engine's kind
        string (exported as a kind label on
        fake_apiserver_chaos_faults_total)."""
        path = f"/api/v1/nodes/{node}"
        try:
            if kind == "node_not_ready":
                server.set_node_ready(node, ready=False)
            elif kind == "node_ready":
                server.set_node_ready(node, ready=True)
            elif kind == "cordon_node":
                server.set_node_unschedulable(node, True)
            elif kind == "uncordon_node":
                server.set_node_unschedulable(node, False)
            else:
                server.evict_pods(node)
        except KeyError:
            return  # no such node: the fault never fired, don't count it
        with self._lock:
            self.fired.append((kind, "CHAOS", path))

    @staticmethod
    def _consume(f: Dict[str, Any]) -> bool:
        """Window/count bookkeeping shared by every fault kind: a fault
        with a ``for`` window fires on every match inside it; otherwise
        ``count`` bounds total firings (absent = every match until
        clear())."""
        if f.get("for") is None and "count" in f:
            left = f.setdefault("_left", f["count"])
            if left <= 0:
                return False
            f["_left"] = left - 1
        return True

    def intercept(self, method: str, path: str, is_watch: bool,
                  is_ssa: bool = False):
        """None (pass through) | ("drop",) | ("stall", secs) |
        ("trickle", bytes_per_sec, body) | ("truncate",) |
        ("garbage", raw_body) | ("status", code, headers, body) for one
        request."""
        with self._lock:
            now = (0.0 if self._t0 is None
                   else time.monotonic() - self._t0)
            for f in self._faults:
                if f.get("flap") or any(k in f for k in _NODE_FAULT_KINDS):
                    continue  # timer-driven, never per-request
                at = f.get("at", 0.0)
                if now < at:
                    continue
                dur = f.get("for")
                if dur is not None and now >= at + dur:
                    continue
                if f.get("method") and f["method"] != method:
                    continue
                if f.get("watch") and not is_watch:
                    continue
                if f.get("ssa") and not is_ssa:
                    continue
                m = f.get("match")
                if m and (path != m if f.get("exact") else m not in path):
                    continue
                if "drop" in f:
                    left = f.setdefault("_left", f["drop"])
                    if left <= 0:
                        continue
                    f["_left"] = left - 1
                    self.fired.append(("drop", method, path))
                    return ("drop",)
                if "stall" in f:
                    if not self._consume(f):
                        continue
                    self.fired.append(("stall", method, path))
                    return ("stall", float(f["stall"]))
                if "trickle" in f:
                    if not self._consume(f):
                        continue
                    self.fired.append(("trickle", method, path))
                    return ("trickle", float(f["trickle"]), f.get("body"))
                if f.get("truncate"):
                    if not self._consume(f):
                        continue
                    self.fired.append(("truncate", method, path))
                    return ("truncate",)
                if f.get("garbage"):
                    if not self._consume(f):
                        continue
                    self.fired.append(("garbage", method, path))
                    return ("garbage", f.get("body"))
                status = f.get("status")
                if status is None:
                    continue
                if not self._consume(f):
                    continue
                headers = {}
                if f.get("retry_after") is not None:
                    headers["Retry-After"] = str(f["retry_after"])
                body = f.get("body") or {
                    "kind": "Status", "code": status, "reason": "Chaos",
                    "message": "injected fault"}
                self.fired.append((status, method, path))
                return ("status", status, headers, body)
        return None


def soak_seconds(default: float) -> float:
    """The soak-duration knob (ISSUE 18): chaos/lockorder soaks run for
    ``max(default, $TPU_SOAK_SECONDS)`` — tier-1 defaults stay untouched
    when the env var is unset/invalid, while CI's slow lane (or a
    developer hunting a rare interleaving) can stretch the same soak to
    minutes or hours without editing a test."""
    import os
    try:
        return max(default, float(os.environ.get("TPU_SOAK_SECONDS", "0")))
    except ValueError:
        return default


def standard_fault_script(unit: float = 0.05) -> List[Dict[str, Any]]:
    """The 'standard' chaos script the soak test and bench share: a 503
    burst with Retry-After from t=0, two dropped connections once it
    clears, then one watch-invalidating apiserver flap. ``unit`` scales
    every timing so the same shape runs as a fast tier-1 case or a long
    soak."""
    return [
        {"at": 0.0, "for": 3 * unit, "status": 503, "retry_after": unit},
        {"at": 3 * unit, "drop": 2},
        {"at": 5 * unit, "flap": True},
    ]


def slow_fault_script(unit: float = 0.05) -> List[Dict[str, Any]]:
    """The SLOW-PATH sibling of :func:`standard_fault_script` (ISSUE 9):
    instead of failing fast, the apiserver goes quiet — one STALLED
    request (accepted, nothing ever sent), one TRICKLED GET body (headers
    at once, then a dribble that defeats per-socket-op timeouts), one
    TRUNCATED chunked watch stream plus one truncated plain reply, and
    two GARBAGE half-JSON 200s. Every fault is count-bounded so a client
    with whole-attempt deadline discipline converges on retries; without
    one, the stall and the trickle park it for ~8*unit each — exactly
    the failure the deadline layer exists for. ``unit`` scales the stall
    duration and the trickle rate the way it scales the standard
    script's windows."""
    trickle_body = {"kind": "Status", "code": 200, "reason": "Chaos",
                    "message": "trickled"}
    body_len = len(json.dumps(trickle_body))
    return [
        {"at": 0.0, "count": 1, "stall": 8 * unit},
        # rate chosen so the full dribble takes ~8*unit — far past any
        # sane per-attempt deadline at that unit
        {"at": 0.0, "count": 1, "method": "GET",
         "trickle": max(1.0, body_len / (8 * unit)), "body": trickle_body},
        {"at": 0.0, "count": 1, "truncate": True, "watch": True},
        {"at": unit, "count": 1, "truncate": True},
        {"at": unit, "count": 2, "garbage": True},
    ]


def make_self_signed(tmp_dir) -> Tuple[str, str]:
    """Generate a 127.0.0.1 self-signed cert+key pair for TLS-mode tests."""
    import subprocess
    cert = f"{tmp_dir}/tls.crt"
    key = f"{tmp_dir}/tls.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    return cert, key


class FakeApiServer:
    """``tls`` = (certfile, keyfile) serves HTTPS — used to exercise the
    operator's in-cluster transport (exec-of-curl with --cacert + bearer
    token) without a real apiserver.

    Restart simulation: ``port`` pins the listen port so a second instance
    can come up where a stopped one was, and ``store`` seeds the object
    store (the bounced apiserver kept etcd). ``ghost_get_404`` lists paths
    whose GET lies 404 while the object IS stored — the stale-read window
    after a bounce/HA failover, where a client's create races the object's
    existence and must handle POST -> 409 AlreadyExists by patching;
    the window clears after the first ghosted read.

    Fault injection: ``chaos`` takes a scripted fault schedule (see
    :class:`ChaosEngine` for the format) armed when the server starts.
    ``reject_posts`` (exact collection path -> status for its POSTs: RBAC
    denial / admission-webhook rejection) and ``reject_watch`` (exact path
    -> status for its ``?watch=1`` GETs: RBAC without the watch verb) are
    legacy sugar, translated into unlimited chaos faults at construction.
    ``watch_gone_once`` lists paths whose NEXT watch emits an ERROR/410
    event and ends — the compacted-history window a real apiserver reports
    when the client's resourceVersion fell off the end of etcd history;
    ``flap()`` (or a ``{"flap": True}`` fault) simulates a full apiserver
    restart, 410-invalidating every in-flight watch AND every pre-restart
    resourceVersion."""

    def __init__(self, auto_ready: bool = True, tls=None, port: int = 0,
                 store: Optional[Dict[str, Dict[str, Any]]] = None,
                 ghost_get_404=(), reject_posts: Optional[Dict[str, int]] = None,
                 latency_s: float = 0.0,
                 reject_watch: Optional[Dict[str, int]] = None,
                 watch_gone_once=(), chaos=None,
                 ssa_unsupported: bool = False,
                 continue_ttl_s: float = 300.0,
                 apf_inflight_budget: Optional[int] = None,
                 apf_retry_after_s: float = 0.05,
                 event_ttl_s: Optional[float] = None):
        self.auto_ready = auto_ready
        # An apiserver predating server-side apply: every
        # application/apply-patch+yaml PATCH answers 415, the capability
        # signal that flips the clients' sticky GET+merge-PATCH fallback.
        self.ssa_unsupported = ssa_unsupported
        # Injected per-request service time (scripts/bench_rollout.py and
        # the shared-watcher tests): slept before EVERY handled request, on
        # that request's own handler thread, so concurrent clients overlap
        # their waits exactly like round trips to a remote apiserver.
        self.latency_s = latency_s
        self._tls = tls
        self.store: Dict[str, Dict[str, Any]] = dict(store or {})  # guarded-by: _lock
        self.ghost_get_404 = set(ghost_get_404)  # guarded-by: _lock
        faults: List[Dict[str, Any]] = []
        for path, rc in (reject_posts or {}).items():
            faults.append({"status": rc, "method": "POST", "match": path,
                           "exact": True,
                           "body": {"kind": "Status", "code": rc,
                                    "reason": "Forbidden"}})
            # The same denial must cover the collection's server-side-apply
            # creates: an RBAC rule that rejects POSTs rejects the
            # equivalent apply PATCH too (kube RBAC gates the verb+resource,
            # not the wire encoding).
            faults.append({"status": rc, "method": "PATCH", "ssa": True,
                           "match": path + "/",
                           "body": {"kind": "Status", "code": rc,
                                    "reason": "Forbidden"}})
        for path, rc in (reject_watch or {}).items():
            faults.append({"status": rc, "watch": True, "match": path,
                           "exact": True,
                           "body": {"kind": "Status", "code": rc,
                                    "reason": "Forbidden"}})
        if chaos is not None:
            faults.extend(chaos)
        self.chaos: Optional[ChaosEngine] = (
            ChaosEngine(faults) if faults else None)
        self.watch_gone_once = set(watch_gone_once)  # guarded-by: _lock
        # (method, path) per request
        self.log: List[Tuple[str, str]] = []  # guarded-by: _lock
        # stored object paths, in order
        self.created: List[str] = []  # guarded-by: _lock
        self.headers_seen: List[Dict[str, str]] = []  # guarded-by: _lock
        # Server-side request audit by (verb, path-sans-query, status):
        # every request that reached a handler gets exactly ONE entry —
        # normal replies, watch streams (status 200), chaos status
        # injections, and dropped connections (status 0) — so
        # sum(responses.values()) == len(log) always, and the
        # /__fake_metrics endpoint can publish it for client-vs-server
        # accounting assertions. Scrapes of /__fake_metrics itself are
        # excluded from BOTH (the observer must not move the needle).
        self.responses: Dict[Tuple[str, str, int], int] = {}  # guarded-by: _responses_lock
        # Server-side SPANS (ISSUE 8): one record per handled request —
        # same coverage contract as `responses` (normal replies, watch
        # streams with their full stream duration, chaos injections,
        # drops as status 0) — tagged with the trace/parent ids parsed
        # from the inbound W3C traceparent header, published as a Chrome
        # trace by /__fake_trace so `tpuctl trace merge` can lay the
        # server's timeline next to the CLI's with shared ids.
        self.spans: List[Dict[str, Any]] = []  # guarded-by: _responses_lock
        # epoch + monotonic anchor pair: span ts values are offsets from
        # _t0_mono, and `epoch` names the wall-clock instant the anchor
        # was taken so merged timelines align across processes (both
        # set once at construction, read-only after)
        self.epoch = time.time()
        self._t0_mono = time.monotonic()
        # own lock: _reply fires inside handlers that already hold _lock
        # (which is non-reentrant), so the audit cannot share it —
        # tests/test_lockorder.py pins the resulting _lock ->
        # _responses_lock edge as the fake's ONLY lock nesting
        self._responses_lock = threading.Lock()
        # Paginated-LIST continuation pages served, by collection path
        # (ISSUE 11): the server-side half of the pagination audit.
        self.list_pages: Dict[str, int] = {}  # guarded-by: _responses_lock
        # ------------------------------------------------------ events
        # (ISSUE 12): real core/v1 Event semantics. POSTed Events are
        # counted by reason (fake_apiserver_events_total on the
        # scrape), stamped with a creation instant, and TTL-compacted
        # the way a real apiserver GCs Events after --event-ttl:
        # event_ttl_s set -> every Event POST first sweeps Events older
        # than the TTL out of the store (watch DELETED events emitted;
        # compact_events() is the explicit test hook). None (default) =
        # Events never expire, byte-identical handling.
        self.event_ttl_s = event_ttl_s
        self.events_posted: Dict[str, int] = {}  # guarded-by: _responses_lock
        self.events_compacted = 0  # guarded-by: _responses_lock
        self._event_created: Dict[str, float] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # -------------------------------------------------- pagination
        # (ISSUE 11): collection GETs honor ?limit=N and ?continue=TOK
        # (apiserver chunked-LIST semantics). A continue token snapshots
        # the item NAME order at first-page time, so pages stay stable
        # under concurrent mutation; tokens expire after continue_ttl_s
        # (or via expire_continue_tokens()) and an expired/unknown token
        # answers 410 Gone reason=Expired — the client must re-LIST from
        # a clean first page, exactly like a real apiserver compaction.
        self.continue_ttl_s = continue_ttl_s
        self._continue_tokens: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._continue_seq = 0  # guarded-by: _lock
        # -------------------------------------------------- APF budget
        # (ISSUE 11): API Priority & Fairness-style load shedding. When
        # apf_inflight_budget is set, a non-watch request arriving while
        # `budget` requests are already inside their service window is
        # answered 429 + Retry-After instead of being handled — the
        # fault the client's retry family (and the never-hedge-a-429
        # pin) must absorb. None (default) = off, byte-identical
        # handling. Own leaf lock: the inflight gate must not nest with
        # the store or audit locks.
        self.apf_inflight_budget = apf_inflight_budget
        self.apf_retry_after_s = apf_retry_after_s
        self._apf_lock = threading.Lock()
        self._apf_inflight = 0  # guarded-by: _apf_lock
        self.apf_rejections = 0  # guarded-by: _apf_lock
        # watch support (?watch=1): every mutation through the HTTP
        # handlers (or the touch() test hook) bumps _rev and records the
        # touched path; watchers block on the condition and stream events
        # for paths under their watch. The changes list is bounded — a
        # watcher always re-reads the CURRENT object, so dropped history
        # only loses intermediate states, like a real compacted etcd.
        self._changed = threading.Condition(self._lock)
        self._rev = 0  # guarded-by: _lock
        # (rev, path) change feed
        self._changes: List[Tuple[int, str]] = []  # guarded-by: _lock
        # bumped by flap(): streams opened under an older epoch end with
        # ERROR/410 — "the apiserver you were watching restarted"
        self._flap_epoch = 0  # guarded-by: _lock
        # Live connections (ISSUE 13): ThreadingHTTPServer's shutdown()
        # stops the LISTENER but not established handler threads, so an
        # in-process "restart" (stop() + a new instance on the pinned
        # port) used to leave ZOMBIE handlers serving the old store —
        # watch streams until their window expired, and plain
        # keep-alive connections (a scraper's, a pooled Client's)
        # INDEFINITELY. stop() severs every live connection so the old
        # world dies NOW; flap() severs only the watch streams (its
        # contract is watch invalidation — the store survives a flap).
        # Both pinned by test_metricsdb's restart test. Own leaf lock:
        # register/sever never nest with _lock or the audit lock (the
        # lockorder soak pins the fake's edge set).
        self._conns: List[Any] = []  # guarded-by: _conns_lock
        self._watch_conns: List[Any] = []  # guarded-by: _conns_lock
        self._conns_lock = threading.Lock()

        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def setup(self):
                super().setup()
                # every connection is severable at stop(): a parked
                # keep-alive handler must die with its "restarted"
                # server, not zombie-serve the old store (see _conns)
                with fake._conns_lock:
                    fake._conns.append(self.connection)

            def finish(self):
                try:
                    super().finish()
                finally:
                    with fake._conns_lock:
                        try:
                            fake._conns.remove(self.connection)
                        except ValueError:
                            pass

            def log_message(self, *args):
                pass

            def _body(self) -> Any:
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                return json.loads(raw) if raw else None

            def _reply(self, code: int, obj: Any = None):
                fake._note_response(self.command,
                                    self.path.partition("?")[0], code)
                self._span(code)
                body = json.dumps(obj if obj is not None else {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _record(self):
                # with the APF budget armed the service-time sleep moves
                # INSIDE the inflight slot (_apf_begin), so concurrent
                # requests overlap in the counted window; budget off =
                # the original sleep-here hot path, byte-identical
                if fake.latency_s > 0 and fake.apf_inflight_budget is None:
                    time.sleep(fake.latency_s)
                # span anchor + inbound trace context, captured before
                # any handling so the server span covers service time
                self._span_t0 = time.monotonic()
                self._rx_traceparent = self.headers.get("traceparent", "")
                with fake._lock:
                    fake.log.append((self.command, self.path))
                    fake.headers_seen.append(dict(self.headers))

            # --------------------------------------------- APF inflight
            # gate (ISSUE 11): _apf_begin claims one service slot (or
            # answers 429 + Retry-After when the budget is full),
            # _apf_end releases it — callers pair them try/finally.
            # Watch streams are EXEMPT from the count (a long-lived
            # stream would consume the budget forever) but still pay the
            # service-time sleep; the budget-off path never touches the
            # APF lock at all.

            def _apf_begin(self, is_watch: bool = False) -> bool:
                """True = proceed (slot held unless exempt); False = a
                429 was sent and the request is done. Must be called
                AFTER the request body has been drained (same keep-alive
                rule as _chaos)."""
                self._apf_held = False
                if fake.apf_inflight_budget is None:
                    return True
                if is_watch:
                    if fake.latency_s > 0:
                        time.sleep(fake.latency_s)
                    return True
                with fake._apf_lock:
                    fake._apf_inflight += 1
                    over = fake._apf_inflight > fake.apf_inflight_budget
                    if over:
                        fake._apf_inflight -= 1
                        fake.apf_rejections += 1
                if over:
                    self._reply_429()
                    return False
                self._apf_held = True
                if fake.latency_s > 0:
                    time.sleep(fake.latency_s)
                return True

            def _apf_end(self) -> None:
                if getattr(self, "_apf_held", False):
                    self._apf_held = False
                    with fake._apf_lock:
                        fake._apf_inflight -= 1

            def _reply_429(self) -> None:
                """APF load-shed reply: 429 + Retry-After (the header the
                client's retry family honors). One audit entry + span
                like every other handled request."""
                path = self.path.partition("?")[0]
                fake._note_response(self.command, path, 429)
                self._span(429, apf=True)
                body = json.dumps({
                    "kind": "Status", "code": 429,
                    "reason": "TooManyRequests",
                    "message": "too many concurrent requests in flight; "
                               "retry after backoff"}).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After",
                                 str(fake.apf_retry_after_s))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _span(self, status: int, **extra: Any):
                """One server-side span for THIS request (same one-entry
                coverage contract as the `responses` audit)."""
                fake._note_span(self.command,
                                self.path.partition("?")[0], status,
                                getattr(self, "_span_t0", None),
                                getattr(self, "_rx_traceparent", ""),
                                **extra)

            def _chaos(self, is_watch: bool = False,
                       is_ssa: bool = False) -> bool:
                """True when a scripted fault consumed this request —
                either an injected status reply was sent, or the
                connection was dropped without one. Must be called AFTER
                the request body has been drained (an unread body would
                be parsed as the next keep-alive request)."""
                if fake.chaos is None:
                    return False
                path = self.path.partition("?")[0]
                act = fake.chaos.intercept(self.command, path, is_watch,
                                           is_ssa)
                if act is None:
                    return False
                if act[0] == "drop":
                    # half-close the socket with no reply: the client sees
                    # the connection die mid-request (RemoteDisconnected /
                    # reset), i.e. transport status 0
                    fake._note_response(self.command, path, 0)
                    self._span(0, chaos="drop")
                    self.close_connection = True
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    return True
                if act[0] == "stall":
                    return self._chaos_stall(path, act[1])
                if act[0] == "trickle":
                    return self._chaos_trickle(path, act[1], act[2])
                if act[0] == "truncate":
                    return self._chaos_truncate(path)
                if act[0] == "garbage":
                    return self._chaos_garbage(path, act[1])
                _, status, headers, body = act
                fake._note_response(self.command, path, status)
                self._span(status, chaos="status")
                payload = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return True

            # --------------------------------------------- slow-path faults
            # (ISSUE 9): the server that is SLOW, not failing fast. Each
            # helper sends (or withholds) bytes itself, records exactly one
            # `responses` audit entry, and spans the request in
            # /__fake_trace with the chaos kind — the span covers the whole
            # slow window, so a merged timeline shows the client attempt
            # and the server dawdling side by side.

            def _chaos_stall(self, path: str, secs: float) -> bool:
                """Accept the request and send NOTHING for ``secs``, then
                sever. A per-socket-op timeout longer than the stall never
                fires (no byte ever arrives to reset it early, none to
                satisfy it) — only a whole-attempt wall deadline gets the
                client unstuck before the stall ends."""
                fake._note_response(self.command, path, 0)
                end = time.monotonic() + secs
                while True:
                    left = end - time.monotonic()
                    if left <= 0:
                        break
                    time.sleep(min(0.05, left))
                self._span(0, chaos="stall")
                self.close_connection = True
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return True

            def _chaos_trickle(self, path: str, bytes_per_sec: float,
                               body: Any) -> bool:
                """200 with full headers at once, then the body dribbled
                one byte at a time at ``bytes_per_sec``. DEFEATS
                per-socket-op timeouts by design: every recv succeeds
                within the op timeout, yet the whole body takes
                len/rate seconds — the fault class the whole-attempt
                deadline exists for. A client that hangs up mid-dribble
                (its deadline fired) is the expected outcome."""
                payload = json.dumps(body if body is not None else {
                    "kind": "Status", "code": 200, "reason": "Chaos",
                    "message": "trickled body"}).encode()
                fake._note_response(self.command, path, 200)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.close_connection = True
                delay = 1.0 / max(1e-6, bytes_per_sec)
                try:
                    for i in range(len(payload)):
                        self.wfile.write(payload[i:i + 1])
                        self.wfile.flush()
                        time.sleep(delay)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # the client gave up — the point of the fault
                self._span(200, chaos="trickle")
                return True

            def _chaos_truncate(self, path: str) -> bool:
                """200 + ``Transfer-Encoding: chunked`` that declares a
                bigger chunk than it delivers, then EOFs: mid-chunked-body
                for plain requests, mid-watch-event for streams. The
                client must classify the cut-off as transport failure
                (IncompleteRead / truncated-chunked), never hand the
                prefix to a JSON parser as a short 200."""
                fake._note_response(self.command, path, 200)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    # a 0x40-byte chunk, half an event delivered, EOF
                    self.wfile.write(
                        b"40\r\n" + b'{"type":"MODIFIED","object":{"kind')
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                self._span(200, chaos="truncate")
                self.close_connection = True
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return True

            def _chaos_garbage(self, path: str, body: Any) -> bool:
                """200 whose body is half-JSON (or any raw override) with
                a CORRECT Content-Length: the framing is healthy, the
                payload is not — the client must classify it into the
                transport-0 retry family, not crash or treat it as a
                parsed object."""
                if body is None:
                    payload = b'{"kind": "Status", "code": 200, "half": '
                elif isinstance(body, bytes):
                    payload = body
                else:
                    payload = str(body).encode()
                fake._note_response(self.command, path, 200)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                try:
                    self.wfile.write(payload)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                self._span(200, chaos="garbage")
                return True

            def _serve_watch(self, path: str, q: Dict[str, list]):
                """`?watch=1` long-poll: stream newline-delimited watch
                events for mutations at/under ``path`` until timeoutSeconds
                elapses, then end the stream cleanly (the apiserver watch
                -window model). Connection: close + no Content-Length —
                the client reads lines until EOF.

                ``?resourceVersion=N`` starts the stream from revision N
                (events with rev > N are replayed), like a watch resumed
                from a LIST's resourceVersion. An RV older than the
                retained change history — or a path armed via the
                ``watch_gone_once`` fault hook — answers with a single
                ERROR/410 event and ends; a flap() ("apiserver restart")
                while the stream is open does the same mid-stream: the
                client must re-LIST and re-watch (real apiserver
                compaction semantics)."""
                try:
                    timeout_s = float(q.get("timeoutSeconds", ["30"])[0])
                except ValueError:
                    timeout_s = 30.0
                deadline = time.monotonic() + max(0.0, min(timeout_s, 300.0))
                fake._note_response("GET", path, 200)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Connection", "close")
                self.end_headers()
                self.close_connection = True
                def send_gone():
                    ev = {"type": "ERROR",
                          "object": {"kind": "Status", "code": 410,
                                     "reason": "Expired"}}
                    try:
                        self.wfile.write((json.dumps(ev) + "\n").encode())
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        pass

                gone = False
                with fake._lock:
                    epoch = fake._flap_epoch
                    if path in fake.watch_gone_once:
                        fake.watch_gone_once.discard(path)
                        gone = True
                    last_rev = fake._rev
                    rv_param = q.get("resourceVersion", [""])[0]
                    if rv_param:
                        try:
                            start = int(rv_param)
                        except ValueError:
                            start = fake._rev
                        oldest = (fake._changes[0][0] if fake._changes
                                  else fake._rev + 1)
                        if start < oldest - 1 and start < fake._rev:
                            gone = True  # history compacted past this RV
                        else:
                            last_rev = start
                if gone:
                    send_gone()
                    return
                try:
                    while True:
                        with fake._changed:
                            while fake._rev == last_rev \
                                    and fake._flap_epoch == epoch:
                                remaining = deadline - time.monotonic()
                                if remaining <= 0:
                                    return  # clean end of the watch window
                                fake._changed.wait(min(remaining, 1.0))
                            if fake._flap_epoch != epoch:
                                # the "apiserver" restarted under this
                                # stream: its history is gone — invalidate
                                # so the client re-LISTs and re-watches
                                invalidated = True
                                events = []
                            else:
                                invalidated = False
                                touched = [p for r, p in fake._changes
                                           if r > last_rev
                                           and (p == path
                                                or p.startswith(path + "/"))]
                                last_rev = fake._rev
                                events = [(p, json.loads(json.dumps(
                                               fake.store[p]))
                                           if p in fake.store else None)
                                          for p in touched]
                        if invalidated:
                            send_gone()
                            return
                        for p, obj in events:
                            if obj is None:
                                ev = {"type": "DELETED",
                                      "object": {"metadata": {
                                          "name": p.rsplit("/", 1)[-1]}}}
                            else:
                                ev = {"type": "MODIFIED", "object": obj}
                            self.wfile.write(
                                (json.dumps(ev) + "\n").encode())
                            self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # watcher went away; nothing to clean up

            def do_GET(self):
                introspect = self.path.partition("?")[0]
                if introspect in ("/__fake_metrics", "/__fake_trace"):
                    # Introspection endpoints (ISSUEs 6/8): the server's
                    # own request accounting as Prometheus text, and its
                    # span log as a Chrome trace. Served OUTSIDE
                    # _record/_chaos — the observer is not part of the
                    # audit, and chaos must not black-hole it.
                    if introspect == "/__fake_metrics":
                        body = fake.fake_metrics_text().encode()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        body = json.dumps(
                            fake.fake_trace(),
                            separators=(",", ":")).encode()
                        ctype = "application/json"
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self._record()
                path, _, query = self.path.partition("?")
                q = parse_qs(query)
                is_watch = q.get("watch", ["0"])[0] in ("1", "true")
                if not self._apf_begin(is_watch):
                    return
                try:
                    if self._chaos(is_watch):
                        return
                    if is_watch:
                        # registered for the restart sever: stop()/
                        # flap() shut this socket down so the stream
                        # dies with the "restarted" server instead of
                        # zombie-serving the old store to window end
                        fake._watch_register(self.connection)
                        try:
                            self._serve_watch(path, q)
                        finally:
                            fake._watch_unregister(self.connection)
                            # the stream's span covers its whole lifetime
                            # — open to window end / invalidation /
                            # client gone
                            self._span(200, watch=True)
                        return
                    page_status = None
                    with fake._lock:
                        obj = fake.store.get(path)
                        if path in fake.ghost_get_404:
                            # stale read: stored but reported absent
                            obj = None
                            fake.ghost_get_404.discard(path)
                        if obj is None and \
                                path.rsplit("/", 1)[-1] in \
                                COLLECTION_SEGMENTS:
                            # collection GET: list stored objects one
                            # level under the path, honoring
                            # ?labelSelector=k=v and ?limit=/?continue=
                            # pagination (ISSUE 11). Gated on known
                            # plural segments so a GET of an absent
                            # OBJECT (e.g. a parent whose seeded
                            # "<path>/status" key exists) still 404s
                            # like a real apiserver.
                            obj, page_status = \
                                fake._collection_page_locked(path, query)
                    if page_status is not None:
                        self._reply(*page_status)
                        return
                    if obj is None:
                        self._reply(404, {"kind": "Status", "code": 404})
                        return
                    if (obj.get("kind") == "List"
                            and (q.get("continue", [""])[0]
                                 or (obj.get("metadata") or {})
                                 .get("continue"))):
                        # one audit bump per served page of a PAGINATED
                        # list (outside _lock; own lock — see
                        # list_pages)
                        fake._note_list_page(path)
                    self._reply(200, obj)
                finally:
                    self._apf_end()

            # requires: fake._lock
            def _finalize_create_locked(self, path: str, obj: Dict[str, Any],
                                        manager: str = "",
                                        intent_fields=None) -> Dict[str, Any]:
                """Stamp a freshly-created object the way the apiserver
                does (uid, generation, auto_ready status + its kubelet
                ownership entry, apply-manager ownership for SSA creates —
                ``intent_fields`` is the field set of the RAW intent, never
                of the stamped object), store it and wake watchers. Caller
                holds fake._lock."""
                obj = dict(obj)
                obj["metadata"] = dict(obj.get("metadata") or {})
                obj["metadata"].setdefault(
                    "uid", f"uid-{len(fake.store) + 1:04d}")
                if obj.get("kind") in GENERATION_KINDS:
                    obj["metadata"]["generation"] = 1
                if manager:
                    obj["metadata"]["managedFields"] = [
                        {"manager": manager, "operation": "Apply",
                         "fieldsV1": intent_fields or {}}]
                if fake.auto_ready:
                    st = ready_status(obj)
                    if st:
                        obj["status"] = st
                        fake._note_kubelet_status(obj)
                fake.store[path] = obj
                fake.created.append(path)
                fake._note_change(path)
                return obj

            def do_POST(self):
                self._record()
                obj = self._body()
                if not self._apf_begin():
                    return
                try:
                    self._do_post(obj)
                finally:
                    self._apf_end()

            def _do_post(self, obj):
                if self._chaos():
                    return
                name = (obj or {}).get("metadata", {}).get("name")
                if not name:
                    self._reply(422, {"message": "metadata.name required"})
                    return
                # Real apiserver core/v1 Event validation: the Event's
                # namespace must agree with involvedObject.namespace —
                # 'default' when the involved object is cluster-scoped.
                is_event = obj.get("kind") == "Event"
                if is_event:
                    ev_ns = obj.get("metadata", {}).get("namespace", "")
                    inv_ns = obj.get("involvedObject", {}).get(
                        "namespace", "")
                    if ev_ns != (inv_ns or "default"):
                        self._reply(422, {
                            "message": "event namespace does not match "
                                       "involvedObject namespace"})
                        return
                    # TTL sweep BEFORE storing (the arriving Event is
                    # by definition the newest); takes fake._lock, so
                    # it must run outside the store hold below
                    fake.compact_events()
                path = f"{self.path.partition('?')[0]}/{name}"
                with fake._lock:
                    if path in fake.store:
                        self._reply(409, {"kind": "Status", "code": 409,
                                          "reason": "AlreadyExists"})
                        return
                    obj = self._finalize_create_locked(path, obj)
                    if is_event:
                        fake._event_created[path] = time.monotonic()
                if is_event:
                    fake._note_event_posted(str(obj.get("reason", "")))
                self._reply(201, obj)

            def do_PUT(self):
                self._record()
                obj = self._body()
                if not self._apf_begin():
                    return
                try:
                    if self._chaos():
                        return
                    with fake._lock:
                        existed = self.path in fake.store
                        fake.store[self.path] = obj
                        fake._note_change(self.path)
                    self._reply(200 if existed else 201, obj)
                finally:
                    self._apf_end()

            def _serve_ssa(self, path: str, q: Dict[str, list],
                           intent: Any):
                """`PATCH application/apply-patch+yaml?fieldManager=M` —
                server-side apply with real KEP-555 semantics: create when
                absent; otherwise conflict-check fields other managers own,
                prune fields M owned before but dropped from this intent,
                apply-merge the rest, and rewrite managedFields. JSON is
                YAML, so the JSON bodies the clients send parse as-is."""
                if fake.ssa_unsupported:
                    # an apiserver predating SSA: the capability signal
                    # the clients' sticky merge fallback keys on
                    self._reply(415, {
                        "kind": "Status", "code": 415,
                        "message": "server-side apply not supported "
                                   "(no application/apply-patch+yaml)"})
                    return
                manager = q.get("fieldManager", [""])[0]
                force = q.get("force", ["false"])[0] in ("true", "1")
                if not manager:
                    self._reply(400, {
                        "kind": "Status", "code": 400,
                        "message": "fieldManager is required for "
                                   "apply-patch requests"})
                    return
                if not isinstance(intent, dict) or not (
                        intent.get("metadata") or {}).get("name"):
                    self._reply(422, {"message": "metadata.name required"})
                    return
                new_fields = field_set(intent)
                new_paths = _leaf_paths(new_fields)
                with fake._lock:
                    cur = fake.store.get(path)
                    if cur is None:
                        obj = self._finalize_create_locked(
                            path, intent, manager=manager,
                            intent_fields=new_fields)
                        self._reply(201, obj)
                        return
                    # per-manager owned leaf-path sets from managedFields
                    entries = (cur.get("metadata") or {}).get(
                        "managedFields") or []
                    owned = {}       # manager -> set of leaf paths
                    operations = {}  # manager -> recorded operation
                    for e in entries:
                        m = e.get("manager")
                        if not m:
                            continue
                        owned[m] = _leaf_paths(e.get("fieldsV1") or {})
                        operations[m] = e.get("operation", "Update")
                    # conflicts: this intent CHANGES a field another
                    # manager owns (equal values co-own without conflict)
                    conflicts = []
                    for p in sorted(new_paths):
                        for other, oset in sorted(owned.items()):
                            if other == manager or p not in oset:
                                continue
                            if _value_at(cur, p) != _value_at(intent, p):
                                conflicts.append((other, p))
                    if conflicts and not force:
                        causes = [{"field": "." + ".".join(p),
                                   "message": f'conflict with "{m}"'}
                                  for m, p in conflicts]
                        first_mgr, first_path = conflicts[0]
                        self._reply(409, {
                            "kind": "Status", "code": 409,
                            "reason": "Conflict",
                            "message": (
                                f"Apply failed with {len(conflicts)} "
                                f"conflict(s): conflict with "
                                f'"{first_mgr}": '
                                + "." + ".".join(first_path)),
                            "details": {"causes": causes}})
                        return
                    for other, p in conflicts:  # force: take ownership
                        owned[other].discard(p)
                    # deep-copy first: pruning below edits nested dicts in
                    # place, and the old stored object may still be mid-
                    # serialization in a concurrent GET handler
                    merged = ssa_merge(json.loads(json.dumps(cur)), intent)
                    # prune: fields this manager owned before but dropped
                    # from the new intent, unless someone else still owns
                    # them
                    for p in sorted(owned.get(manager, set()) - new_paths):
                        if any(p in oset for m, oset in owned.items()
                               if m != manager):
                            continue
                        _delete_at(merged, p)
                    owned[manager] = new_paths
                    operations[manager] = "Apply"
                    merged["metadata"] = dict(merged.get("metadata") or {})
                    merged["metadata"]["managedFields"] = [
                        {"manager": m, "operation": operations[m],
                         "fieldsV1": _paths_to_fields(paths)}
                        for m, paths in sorted(owned.items()) if paths]
                    # spec changes bump generation, exactly like the
                    # merge-PATCH path
                    if (merged.get("kind") in GENERATION_KINDS
                            and merged.get("spec") != cur.get("spec")):
                        merged["metadata"]["generation"] = \
                            cur.get("metadata", {}).get("generation", 1) + 1
                    if fake.auto_ready and "status" not in intent:
                        st = ready_status(merged)
                        if st:
                            merged["status"] = st
                            fake._note_kubelet_status(merged)
                    fake.store[path] = merged
                    fake._note_change(path)
                self._reply(200, merged)

            def do_PATCH(self):
                self._record()
                patch = self._body()
                if not self._apf_begin():
                    return
                try:
                    self._do_patch(patch)
                finally:
                    self._apf_end()

            def _do_patch(self, patch):
                ctype = self.headers.get("Content-Type") or ""
                is_ssa = ctype.startswith("application/apply-patch+yaml")
                if self._chaos(is_ssa=is_ssa):
                    return
                if is_ssa:
                    path, _, query = self.path.partition("?")
                    self._serve_ssa(path, parse_qs(query), patch)
                    return
                # Status subresource: PATCH <object>/status applies only the
                # patch's status field to the parent object and never bumps
                # metadata.generation (real-apiserver semantics; the
                # operator's TpuStackPolicy status write-back relies on it).
                # Tests that seed the literal "<path>/status" key keep the
                # original flat-store simplification instead.
                if self.path.endswith("/status"):
                    parent_path = self.path[: -len("/status")]
                    subresource = False
                    parent: Optional[Dict[str, Any]] = None
                    with fake._lock:
                        # the membership probe reads the store too — one
                        # lock hold covers probe and patch (conlint CL01
                        # caught the probe outside it)
                        if self.path not in fake.store:
                            subresource = True
                            parent = fake.store.get(parent_path)
                            if parent is not None:
                                st = (patch or {}).get("status")
                                parent["status"] = merge_patch(
                                    parent.get("status"), st)
                                fake._note_change(parent_path)
                    if subresource:
                        if parent is None:
                            self._reply(404,
                                        {"kind": "Status", "code": 404})
                        else:
                            self._reply(200, parent)
                        return
                with fake._lock:
                    cur = fake.store.get(self.path)
                    if cur is None:
                        self._reply(404, {"kind": "Status", "code": 404})
                        return
                    merged = merge_patch(cur, patch)
                    # A spec change bumps metadata.generation (apiserver
                    # behavior); the stored status keeps the old
                    # observedGeneration until "the controller" catches up.
                    if (merged.get("kind") in GENERATION_KINDS
                            and isinstance(patch, dict) and "spec" in patch
                            and merged.get("spec") != cur.get("spec")):
                        merged["metadata"] = dict(merged.get("metadata") or {})
                        merged["metadata"]["generation"] = \
                            cur.get("metadata", {}).get("generation", 1) + 1
                    if fake.auto_ready and not (isinstance(patch, dict)
                                                and "status" in patch):
                        # auto_ready simulates an instantly-converging
                        # cluster: refresh status to the (possibly bumped)
                        # generation unless the patch set status itself.
                        st = ready_status(merged)
                        if st:
                            merged["status"] = st
                    fake.store[self.path] = merged
                    fake._note_change(self.path)
                self._reply(200, merged)

            def do_DELETE(self):
                self._record()
                if not self._apf_begin():
                    return
                try:
                    if self._chaos():
                        return
                    with fake._lock:
                        gone = fake.store.pop(self.path, None)
                        if gone is not None:
                            fake._note_change(self.path)
                    self._reply(200 if gone is not None else 404, {})
                finally:
                    self._apf_end()

        class Server(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                import sys as _sys
                exc = _sys.exc_info()[1]
                if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
                    return  # client went away mid-reply — routine when a
                            # watcher (or a killed operator) disconnects
                super().handle_error(request, client_address)

        self._server = Server(("127.0.0.1", port), Handler)
        if tls is not None:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls[0], keyfile=tls[1])
            self._server.socket = ctx.wrap_socket(self._server.socket,
                                                  server_side=True)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "FakeApiServer":
        self._thread.start()
        if self.chaos is not None:
            self.chaos.start(self)  # the fault clock runs from serve time
        return self

    def stop(self):
        if self.chaos is not None:
            self.chaos.stop()
        # listener down FIRST (shutdown blocks until the accept loop
        # exits, so no new handler can register), THEN sever every
        # established connection — watch streams AND parked keep-alive
        # ones — which would otherwise keep serving the old store, a
        # zombie the client holding them never noticed (see _conns).
        # Severing first would race a connection accepted between the
        # snapshot and the shutdown.
        self._server.shutdown()
        self._sever_all()
        self._server.server_close()

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------- metrics

    def _note_response(self, method: str, path: str, status: int) -> None:
        """One audit entry per handled request (see ``responses``)."""
        key = (method, path, status)
        with self._responses_lock:
            self.responses[key] = self.responses.get(key, 0) + 1

    def _note_list_page(self, path: str) -> None:
        """Count one served page of a PAGINATED collection LIST (a reply
        that carried or consumed a continue token) — published as
        fake_apiserver_list_pages_total{path}."""
        with self._responses_lock:
            self.list_pages[path] = self.list_pages.get(path, 0) + 1

    # ------------------------------------------------------------- events

    def _note_event_posted(self, reason: str) -> None:
        """Count one stored Event create by reason — published as
        fake_apiserver_events_total{reason}."""
        with self._responses_lock:
            self.events_posted[reason] = \
                self.events_posted.get(reason, 0) + 1

    def compact_events(self) -> List[str]:
        """TTL-compact stored Events (a real apiserver GCs Events after
        ``--event-ttl``, default 1h): every Event older than
        ``event_ttl_s`` leaves the store with a watch DELETED event.
        Runs automatically before each Event POST; this is also the
        explicit test hook. No-op (empty list) when event_ttl_s is
        None."""
        if self.event_ttl_s is None:
            return []
        cutoff = time.monotonic() - self.event_ttl_s
        with self._lock:
            victims = sorted(p for p, t in self._event_created.items()
                             if t <= cutoff)
            for p in victims:
                self._event_created.pop(p, None)
                if self.store.pop(p, None) is not None:
                    self._note_change(p)
        if victims:
            with self._responses_lock:
                self.events_compacted += len(victims)
        return victims

    # --------------------------------------------------------- pagination

    # requires: self._lock
    def _new_continue_locked(self, path: str, names: List[str],
                             offset: int, rev: str) -> str:
        """Mint a continue token snapshotting the remaining item-name
        order (apiserver chunked-LIST semantics: pages come from the
        first page's snapshot, at its resourceVersion). Caller holds
        self._lock."""
        self._continue_seq += 1
        token = f"ct-{self._continue_seq:06d}"
        self._continue_tokens[token] = {
            "path": path, "names": list(names), "offset": offset,
            "rev": rev,
            "expires": time.monotonic() + self.continue_ttl_s}
        if len(self._continue_tokens) > 256:
            # bounded, oldest-first: an abandoned chase must not leak
            for k in sorted(self._continue_tokens)[
                    :len(self._continue_tokens) - 256]:
                self._continue_tokens.pop(k, None)
        return token

    # requires: self._lock
    def _collection_page_locked(self, path: str, query: str):
        """One collection-LIST reply body honoring ``?labelSelector=``,
        ``?limit=`` and ``?continue=``: ``(listing, None)`` for a 200,
        ``(None, (status, body))`` for an error reply — today only the
        410 Gone reason=Expired an expired/unknown continue token earns
        (the client must restart from a clean first page). Caller holds
        self._lock."""
        q = parse_qs(query)
        prefix = path.rstrip("/") + "/"
        items = [o for p, o in self.store.items()
                 if p.startswith(prefix) and "/" not in p[len(prefix):]]
        items = _filter_selector(items, query)
        token = q.get("continue", [""])[0]
        try:
            limit = int(q.get("limit", ["0"])[0])
        except ValueError:
            limit = 0
        if token:
            rec = self._continue_tokens.get(token)
            if rec is None or rec["path"] != path \
                    or time.monotonic() >= rec["expires"]:
                self._continue_tokens.pop(token, None)
                return None, (410, {
                    "kind": "Status", "code": 410, "reason": "Expired",
                    "message": "The provided continue parameter is too "
                               "old to display a consistent list result; "
                               "start a new list without the continue "
                               "parameter"})
            # single-use: each page mints the NEXT token (and a client
            # retry of a consumed page re-LISTs cleanly via the 410)
            self._continue_tokens.pop(token, None)
            names = rec["names"]
            offset = int(rec["offset"])
            by_name = {str((o.get("metadata") or {}).get("name", "")): o
                       for o in items}
            page_names = (names[offset:offset + limit] if limit > 0
                          else names[offset:])
            page = [by_name[n] for n in page_names if n in by_name]
            meta: Dict[str, Any] = {"resourceVersion": rec["rev"]}
            next_offset = offset + len(page_names)
            if limit > 0 and next_offset < len(names):
                meta["continue"] = self._new_continue_locked(
                    path, names, next_offset, rec["rev"])
            return {"kind": "List", "metadata": meta, "items": page}, None
        rev = str(self._rev)
        if limit > 0 and len(items) > limit:
            # deterministic page order: sorted by name, like a real
            # apiserver's etcd key order (unpaginated lists keep the
            # historical store order)
            items = sorted(items, key=lambda o: str(
                (o.get("metadata") or {}).get("name", "")))
            names = [str((o.get("metadata") or {}).get("name", ""))
                     for o in items]
            meta = {"resourceVersion": rev,
                    "continue": self._new_continue_locked(
                        path, names, limit, rev)}
            return {"kind": "List", "metadata": meta,
                    "items": items[:limit]}, None
        return {"kind": "List", "metadata": {"resourceVersion": rev},
                "items": items}, None

    def expire_continue_tokens(self) -> None:
        """Force every outstanding continue token expired — the test
        hook for the 410 re-LIST path (no sleeping past
        continue_ttl_s)."""
        with self._lock:
            for rec in self._continue_tokens.values():
                rec["expires"] = 0.0

    def _note_span(self, method: str, path: str, status: int,
                   t_start: Optional[float], traceparent: str,
                   **extra: Any) -> None:
        """One server-side span per handled request (see ``spans``):
        start/duration from the handler's anchor, trace/parent ids from
        the inbound traceparent header (empty when the client sent
        none — telemetry-off clients stay uncorrelated, not broken)."""
        now = time.monotonic()
        start = t_start if t_start is not None else now
        trace_id, parent_id = parse_traceparent(traceparent)
        rec = {"name": f"{method} {path}", "verb": method, "path": path,
               "status": status,
               "ts_s": max(0.0, start - self._t0_mono),
               "dur_s": max(0.0, now - start),
               "tid": threading.get_ident(),
               "trace_id": trace_id, "parent_id": parent_id}
        rec.update(extra)
        with self._responses_lock:
            self.spans.append(rec)

    def fake_trace(self) -> Dict[str, Any]:
        """The `/__fake_trace` body: every server-side span as a Chrome
        trace-event document (cat "server", one ph=X event per handled
        request, args carrying verb/path/status and the inbound
        trace/parent ids) — the middle track of a `tpuctl trace merge`
        timeline."""
        with self._responses_lock:
            spans = [dict(s) for s in self.spans]
        events = []
        for s in spans:
            args = {k: v for k, v in s.items()
                    if k not in ("name", "ts_s", "dur_s", "tid")}
            events.append({
                "name": s["name"], "cat": "server", "ph": "X",
                "ts": round(s["ts_s"] * 1e6, 1),
                "dur": round(s["dur_s"] * 1e6, 1),
                "pid": 1, "tid": s["tid"], "args": args,
            })
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"producer": "fake-apiserver",
                              "epoch": self.epoch}}

    def fake_metrics_text(self) -> str:
        """The `/__fake_metrics` body: the request audit as Prometheus
        text — `fake_apiserver_requests_total{verb,path,code}` (one
        sample per distinct triple; dropped connections are code="0"),
        plus `fake_apiserver_chaos_faults_total{kind}` from the chaos
        engine's fired list. Label order is fixed and families sorted so
        scrapes are byte-stable for equal state. Path labels are
        CLIENT-CONTROLLED bytes and escaped per the exposition format
        (backslash, quote, newline) — a hostile request path must not be
        able to forge extra samples into the scrape."""
        with self._responses_lock:
            rows = sorted(self.responses.items())
        lines = ["# TYPE fake_apiserver_requests_total counter"]
        for (method, path, status), n in rows:
            lines.append(
                f'fake_apiserver_requests_total{{verb="{prom_escape(method)}",'
                f'path="{prom_escape(path)}",code="{status}"}} {n}')
        fired: Dict[str, int] = {}
        if self.chaos is not None:
            for status, _m, _p in self.chaos.fired_snapshot():
                kind = str(status)
                fired[kind] = fired.get(kind, 0) + 1
        lines.append("# TYPE fake_apiserver_chaos_faults_total counter")
        for kind in sorted(fired):
            lines.append(
                f'fake_apiserver_chaos_faults_total{{kind="{kind}"}} '
                f"{fired[kind]}")
        with self._responses_lock:
            pages = sorted(self.list_pages.items())
        lines.append("# TYPE fake_apiserver_list_pages_total counter")
        for path, n in pages:
            lines.append(
                f'fake_apiserver_list_pages_total{{path='
                f'"{prom_escape(path)}"}} {n}')
        with self._apf_lock:
            rejected = self.apf_rejections
        lines.append("# TYPE fake_apiserver_apf_rejections_total counter")
        lines.append('fake_apiserver_apf_rejections_total'
                     f'{{reason="inflight"}} {rejected}')
        with self._responses_lock:
            ev_rows = sorted(self.events_posted.items())
            compacted = self.events_compacted
        lines.append("# TYPE fake_apiserver_events_total counter")
        for reason, n in ev_rows:
            lines.append(
                f'fake_apiserver_events_total{{reason='
                f'"{prom_escape(reason)}"}} {n}')
        lines.append("# TYPE fake_apiserver_events_compacted_total counter")
        lines.append(f"fake_apiserver_events_compacted_total {compacted}")
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------- watch

    # requires: self._lock
    def _note_change(self, path: str) -> None:
        """Record a mutation for watchers and stamp the object's
        metadata.resourceVersion (apiserver behavior — clients resume
        watches from it). Caller must hold self._lock."""
        self._rev += 1
        obj = self.store.get(path)
        if isinstance(obj, dict):
            meta = obj.setdefault("metadata", {})
            if isinstance(meta, dict):
                meta["resourceVersion"] = str(self._rev)
        self._changes.append((self._rev, path))
        del self._changes[:-1000]  # bounded; watchers re-read current state
        self._changed.notify_all()

    # requires: self._lock
    def _note_kubelet_status(self, obj: Dict[str, Any]) -> None:
        """Record the node agent's ownership of ``status`` in
        managedFields whenever auto_ready writes one — real clusters show
        exactly this (kubelet / controller status writers appear as
        non-Apply managers), and the ownership-drift check must know to
        tolerate it. Caller must hold self._lock."""
        meta = obj.setdefault("metadata", {})
        entries = meta.setdefault("managedFields", [])
        for e in entries:
            if e.get("manager") == "kubelet":
                e["fieldsV1"] = {"f:status": {}}
                return
        entries.append({"manager": "kubelet", "operation": "Update",
                        "fieldsV1": {"f:status": {}}})

    def touch(self, path: str) -> None:
        """Wake watchers after a DIRECT store mutation (tests that edit
        ``api.store[...]`` in place bypass the HTTP handlers and their
        notifications)."""
        with self._lock:
            self._note_change(path)

    def flap(self) -> None:
        """Simulate an apiserver restart: the change history compacts (a
        watch resumed from any pre-flap resourceVersion gets ERROR/410)
        and every in-flight watch stream is invalidated with ERROR/410 —
        clients must re-LIST and re-watch. The store itself survives (etcd
        outlived the restart), and the revision counter jumps the way a
        restarted apiserver's resourceVersions do. Streams parked in a
        blocking send (or opened a breath before the epoch bump) are
        additionally SEVERED — outside the store lock — so no watch
        handler can keep serving pre-flap state past the restart."""
        with self._lock:
            self._rev += 1000
            self._changes.clear()
            self._flap_epoch += 1
            self._changed.notify_all()
        self._sever_watches()

    # Severing helpers: each takes ONLY the leaf _conns_lock (the
    # lockorder soak pins the fake's edge set — severing must not nest
    # under _lock). shutdown(SHUT_RDWR) is the only thing that
    # reliably unblocks both a handler's next write and the client's
    # blocking readline (the PR 9 sever rule); handler threads then
    # unwind through their BrokenPipe handling and unregister.

    def _watch_register(self, conn) -> None:
        with self._conns_lock:
            self._watch_conns.append(conn)

    def _watch_unregister(self, conn) -> None:
        with self._conns_lock:
            try:
                self._watch_conns.remove(conn)
            except ValueError:
                pass

    def _sever_all(self) -> None:
        """Sever EVERY live connection (the stop()/restart path)."""
        with self._conns_lock:
            conns = list(self._conns)
        self._shutdown_conns(conns)

    def _sever_watches(self) -> None:
        """Sever only the watch streams (the flap() contract: watches
        invalidate, plain connections survive a flap like they survive
        a real apiserver's graceful watch compaction)."""
        with self._conns_lock:
            conns = list(self._watch_conns)
        self._shutdown_conns(conns)

    @staticmethod
    def _shutdown_conns(conns) -> None:
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    # ------------------------------------------------------------- test hooks

    def paths(self, kind_suffix: str = "") -> List[str]:
        with self._lock:
            return [p for p in self.store if kind_suffix in p]

    def get(self, path: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            obj = self.store.get(path)
            return json.loads(json.dumps(obj)) if obj else None

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Deep copy of the store under the lock (restart-carryover seed)."""
        with self._lock:
            return json.loads(json.dumps(self.store))

    def set_ready(self, path: str, ready: bool = True):
        """Flip a workload object's readiness (the node-simulator stand-in)."""
        with self._lock:
            obj = self.store[path]
            st = ready_status(obj) or {}
            if not ready:
                st = {k: 0 for k in st}
                if obj.get("kind") == "DaemonSet":
                    st["desiredNumberScheduled"] = 2
            obj["status"] = st
            self._note_change(path)

    def delete(self, path: str):
        with self._lock:
            if self.store.pop(path, None) is not None:
                self._note_change(path)

    # ------------------------------------------------- node lifecycle
    # (ISSUE 10): the failure-domain hooks the gang-admission scenarios
    # script — also reachable from a chaos schedule as the
    # node_not_ready / node_ready / evict_pods fault kinds.

    def set_node_ready(self, name: str, ready: bool = True) -> None:
        """Flip a Node's Ready condition (NotReady = the kubelet went
        dark; the admission loop must drain every gang reservation
        touching the host). Raises KeyError for an unknown node."""
        path = f"/api/v1/nodes/{name}"
        with self._lock:
            obj = self.store[path]
            status = obj.setdefault("status", {})
            conds = [c for c in status.get("conditions") or []
                     if not (isinstance(c, dict)
                             and c.get("type") == "Ready")]
            conds.append({"type": "Ready",
                          "status": "True" if ready else "False"})
            status["conditions"] = conds
            self._note_change(path)

    def set_node_unschedulable(self, name: str,
                               unschedulable: bool = True) -> None:
        """Cordon/uncordon a Node: round-trips ``spec.unschedulable``
        through the store with a watch event, exactly like a kubectl
        cordon PATCH would (ISSUE 18). Raises KeyError for an unknown
        node."""
        path = f"/api/v1/nodes/{name}"
        with self._lock:
            obj = self.store[path]
            spec = obj.setdefault("spec", {})
            if unschedulable:
                spec["unschedulable"] = True
            else:
                spec.pop("unschedulable", None)
            self._note_change(path)

    def set_node_version(self, name: str, version: str) -> None:
        """The kubelet hook a simulated device-plugin/libtpu upgrade
        rides (ISSUE 18): rewrite the Node's stack-version label and
        kubelet-reported version, emitting a watch event. Raises
        KeyError for an unknown node."""
        path = f"/api/v1/nodes/{name}"
        with self._lock:
            obj = self.store[path]
            labels = (obj.setdefault("metadata", {})
                      .setdefault("labels", {}))
            labels[FLEET_VERSION_LABEL] = version
            info = (obj.setdefault("status", {})
                    .setdefault("nodeInfo", {}))
            info["kubeletVersion"] = version
            self._note_change(path)

    def evict_pods(self, node_name: str) -> List[str]:
        """Evict (delete) every Pod bound to ``node_name``
        (spec.nodeName), emitting watch DELETED events — what the
        eviction API does when a NotReady node is drained. Returns the
        deleted pod paths. Raises KeyError for an unknown node (an
        eviction against nothing is a script bug, not a no-op)."""
        node_path = f"/api/v1/nodes/{node_name}"
        with self._lock:
            if node_path not in self.store:
                raise KeyError(node_path)
            victims = [
                p for p, o in self.store.items()
                if isinstance(o, dict) and o.get("kind") == "Pod"
                and (o.get("spec") or {}).get("nodeName") == node_name]
            for p in victims:
                self.store.pop(p, None)
                self._note_change(p)
        return victims

    def creation_order(self) -> List[str]:
        with self._lock:
            return list(self.created)
