"""Clusterless pinning of scripts/kind-integration.sh (round-1 verdict
weak #2: the script skips where docker is absent, so nothing locally proved
its pieces stay valid). Docker/kind can't run here, but everything the
script feeds the cluster can: the embedded cluster-spec heredoc is extracted
from the script text and pushed through the real render path, so a spec/
renderer change that would break the CI job fails HERE first."""

import os
import re
import shutil
import subprocess

import pytest

from tpu_cluster import spec as specmod
from tpu_cluster.render import manifests

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "kind-integration.sh")


def embedded_spec_text() -> str:
    text = open(SCRIPT, encoding="utf-8").read()
    m = re.search(r'cat >"\$SPEC" <<EOF\n(.*?)\nEOF\n', text, re.S)
    assert m, "spec heredoc not found in kind-integration.sh"
    return m.group(1).replace("$IMG", "tpu-stack:it")


def test_script_is_valid_bash():
    if not shutil.which("bash"):
        pytest.skip("no bash")
    subprocess.run(["bash", "-n", SCRIPT], check=True)


def test_embedded_spec_renders_fake_device_stack():
    spec = specmod.load(embedded_spec_text())
    objs = manifests.render_objects(spec)
    names = {o["metadata"]["name"] for o in objs if o["kind"] == "DaemonSet"}
    # disabled on TPU-less kind nodes
    assert "tpu-libtpu-prep" not in names
    assert "tpu-node-status-exporter" not in names
    # the §3.4 trace operands the script asserts on
    assert {"tpu-device-plugin", "tpu-feature-discovery",
            "tpu-metrics-exporter"} <= names
    plugin = next(o for o in objs
                  if o["kind"] == "DaemonSet"
                  and o["metadata"]["name"] == "tpu-device-plugin")
    container = plugin["spec"]["template"]["spec"]["containers"][0]
    assert "--fake-devices=8" in container["args"]
    assert container["image"] == "tpu-stack:it"


def test_script_helm_values_match_chart():
    """Every --set key the script's helm exercise uses must exist in the
    chart's values.yaml (a renamed value would fail only in CI)."""
    import yaml
    text = open(SCRIPT, encoding="utf-8").read()
    values = yaml.safe_load(open(os.path.join(
        REPO, "deploy", "chart", "tpu-stack", "values.yaml")))
    for key in re.findall(r"--set (\S+)=", text):
        node = values
        for part in key.split("."):
            assert isinstance(node, dict) and part in node, \
                f"--set {key} not in chart values"
            node = node[part]
