"""Device discovery + feature-discovery label tests (fake device tree)."""

import json

from tpu_cluster.discovery import devices, labeler, labels


def test_discover_fake_tree(tmp_path):
    devices.make_fake_tree(str(tmp_path), 8)
    found = devices.discover("/dev/accel*", devfs_root=str(tmp_path))
    assert [d.index for d in found] == list(range(8))
    assert found[3].path.endswith("dev/accel3")
    assert not found[0].vfio


def test_discover_empty(tmp_path):
    assert devices.discover("/dev/accel*", devfs_root=str(tmp_path)) == []


def test_discover_vfio(tmp_path):
    devices.make_fake_tree(str(tmp_path), 4, vfio=True)
    found = devices.discover_vfio(devfs_root=str(tmp_path))
    assert [d.index for d in found] == [0, 1, 2, 3]
    assert all(d.vfio for d in found)


def test_labels_present(tmp_path):
    devices.make_fake_tree(str(tmp_path), 8)
    found = devices.discover("/dev/accel*", devfs_root=str(tmp_path))
    got = labels.compute_labels("v5e-8", found, "node-1")
    assert got == {
        "google.com/tpu.present": "true",
        "google.com/tpu.accelerator-type": "v5e-8",
        "google.com/tpu.generation": "v5e",
        "google.com/tpu.topology": "2x4",
        "google.com/tpu.count": "8",
        "google.com/tpu.ici-domain": "node-1",
    }


def test_labels_absent_deletes_stale_keys():
    got = labels.compute_labels("v5e-8", [])
    assert got["google.com/tpu.present"] == "false"
    # every other key maps to None -> JSON null -> strategic-merge delete
    for key in labels.ALL_KEYS:
        if key != labels.PRESENT:
            assert got[key] is None
    patch = labeler.node_patch(got)
    assert b'"google.com/tpu.count": null' in patch


def test_labeler_fatal_config_errors(tmp_path, capsys):
    # unknown accelerator -> exit 2, not an eternal retry loop
    rc = labeler.main(["--accelerator=v99", "--oneshot", "--print"])
    assert rc == 2
    assert "fatal" in capsys.readouterr().err
    # missing NODE_NAME in patch mode -> exit 2
    import os
    old = os.environ.pop("NODE_NAME", None)
    try:
        rc = labeler.main(["--accelerator=v5e-8", "--oneshot"])
        assert rc == 2
        assert "NODE_NAME" in capsys.readouterr().err
    finally:
        if old is not None:
            os.environ["NODE_NAME"] = old


def test_labeler_oneshot_outfile(tmp_path):
    devices.make_fake_tree(str(tmp_path), 8)
    out = tmp_path / "labels.jsonl"
    rc = labeler.main([
        "--accelerator=v5e-8", f"--devfs-root={tmp_path}",
        "--oneshot", f"--out-file={out}",
    ])
    assert rc == 0
    rec = json.loads(out.read_text().strip())
    assert rec["labels"]["google.com/tpu.present"] == "true"
    assert rec["labels"]["google.com/tpu.count"] == "8"
    assert "condition" not in rec  # --conditions off


def test_tpu_ready_condition_states():
    """TpuReady condition (node-problem-detector analog, SURVEY.md §5)."""
    ok = labeler.tpu_ready_condition("v5e-8", 8)
    assert ok["type"] == "TpuReady" and ok["status"] == "True"
    assert ok["reason"] == "AllChipsPresent"
    degraded = labeler.tpu_ready_condition("v5e-8", 5)
    assert degraded["status"] == "False"
    assert degraded["reason"] == "DegradedChipSet"
    assert "5/8" in degraded["message"]
    none = labeler.tpu_ready_condition("v5e-8", 0)
    assert none["status"] == "False" and none["reason"] == "NoTpuDevices"
    # status patch body merges by condition type
    body = json.loads(labeler.status_patch(ok))
    assert body == {"status": {"conditions": [ok]}}
    # transition time is preserved across same-status heartbeats and reset
    # on a status flip
    first = labeler.tpu_ready_condition("v5e-8", 8, now="T1")
    assert first["lastTransitionTime"] == "T1"
    second = labeler.tpu_ready_condition("v5e-8", 8, now="T2",
                                         previous=first)
    assert second["lastTransitionTime"] == "T1"
    assert second["lastHeartbeatTime"] == "T2"
    flipped = labeler.tpu_ready_condition("v5e-8", 5, now="T3",
                                          previous=second)
    assert flipped["lastTransitionTime"] == "T3"


def test_labeler_conditions_flag(tmp_path, capsys):
    devices.make_fake_tree(str(tmp_path), 8)
    rc = labeler.main([
        "--accelerator=v5e-8", f"--devfs-root={tmp_path}",
        "--oneshot", "--print", "--conditions",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["labels"]["google.com/tpu.present"] == "true"
    assert rec["condition"]["status"] == "True"
    assert rec["condition"]["lastHeartbeatTime"].endswith("Z")


# ---------------------------------------------------------------- native tfd
# The deployed feature-discovery operand is the C++ tpu-tfd daemon
# (native/discovery/tfd_main.cc); this Python module is its oracle. These
# tests run both against identical fake device trees and diff the JSON
# records (timestamps normalized), then drive the native daemon's publish
# path against the fake apiserver.

import os
import subprocess
import sys

from fake_apiserver import FakeApiServer


def _tfd(native_build):
    return os.path.join(native_build, "tpu-tfd")


def _normalize(rec):
    cond = rec.get("condition")
    if cond:
        for key in ("lastHeartbeatTime", "lastTransitionTime"):
            assert cond[key].endswith("Z")
            cond[key] = "<time>"
    return rec


def _run_record(cmd, env_extra=None):
    env = dict(os.environ, **(env_extra or {}))
    out = subprocess.run(cmd, check=True, capture_output=True, env=env,
                         text=True).stdout
    return json.loads(out.strip())


def _python_labeler_cmd(*args):
    return [sys.executable, "-m", "tpu_cluster.discovery.labeler", *args]


def test_native_tfd_matches_python_oracle(native_build, tmp_path):
    """C++ and Python label/condition records agree on every tree shape."""
    trees = {}
    for name, n, vfio in [("full", 8, False), ("degraded", 5, False),
                          ("empty", 0, False), ("vfio", 8, True)]:
        root = tmp_path / name
        devices.make_fake_tree(str(root), n, vfio=vfio)
        trees[name] = str(root)
    for name, root in trees.items():
        args = ["--print", "--oneshot", "--conditions",
                "--accelerator=v5e-8", f"--devfs-root={root}"]
        env = {"NODE_NAME": "node-x"}
        got_cpp = _normalize(_run_record([_tfd(native_build), *args], env))
        got_py = _normalize(_run_record(_python_labeler_cmd(*args), env))
        assert got_cpp == got_py, f"tree {name}: native != oracle"


def test_native_tfd_outfile_and_unknown_accelerator(native_build, tmp_path):
    devices.make_fake_tree(str(tmp_path), 8)
    out = tmp_path / "rec.jsonl"
    subprocess.run(
        [_tfd(native_build), "--oneshot", f"--devfs-root={tmp_path}",
         f"--out-file={out}"], check=True)
    rec = json.loads(out.read_text().strip())
    assert rec["labels"]["google.com/tpu.count"] == "8"
    assert "condition" not in rec
    # unknown accelerator -> exit 2 (CrashLoopBackOff signal), like the oracle
    proc = subprocess.run([_tfd(native_build), "--accelerator=v99",
                           "--oneshot", "--print"], capture_output=True)
    assert proc.returncode == 2
    assert b"fatal" in proc.stderr


def test_native_tfd_patches_node_via_apiserver(native_build, tmp_path):
    """Publish path: labels PATCH on the Node, TpuReady on nodes/status."""
    devices.make_fake_tree(str(tmp_path), 8)
    with FakeApiServer() as api:
        # seed the Node object (PATCH on a missing path 404s, like the real
        # apiserver for a node that doesn't exist)
        import urllib.request
        for path, body in [
            ("/api/v1/nodes/node-x",
             {"kind": "Node", "metadata": {"name": "node-x", "labels": {
                 "google.com/tpu.count": "7"}}}),
            # the fake stores the status subresource at its literal path
            ("/api/v1/nodes/node-x/status", {"status": {"conditions": []}}),
        ]:
            req = urllib.request.Request(
                api.url + path, data=json.dumps(body).encode(),
                method="PUT", headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req)
        env = dict(os.environ, NODE_NAME="node-x")
        subprocess.run(
            [_tfd(native_build), "--oneshot", "--conditions",
             f"--devfs-root={tmp_path}", f"--apiserver={api.url}"],
            check=True, env=env, capture_output=True)
        node = api.get("/api/v1/nodes/node-x")
        assert node["metadata"]["labels"]["google.com/tpu.count"] == "8"
        assert node["metadata"]["labels"]["google.com/tpu.present"] == "true"
        status = api.get("/api/v1/nodes/node-x/status")
        conds = status["status"]["conditions"]
        assert conds and conds[0]["type"] == "TpuReady"
        assert conds[0]["status"] == "True"
        patches = [(m, p) for (m, p) in api.log if m == "PATCH"]
        assert ("PATCH", "/api/v1/nodes/node-x") in patches
        assert ("PATCH", "/api/v1/nodes/node-x/status") in patches
        ctypes = [h.get("Content-Type") for h in api.headers_seen
                  if h.get("Content-Type")]
        assert "application/strategic-merge-patch+json" in ctypes


def test_native_tfd_preserves_transition_time_across_cycles(native_build,
                                                            tmp_path):
    """Kubelet-condition semantics in the live daemon: heartbeats advance
    but lastTransitionTime only moves when the status flips (answerable
    'how long has this node been degraded'). The oneshot oracle tests can't
    see this — it needs consecutive cycles in one process."""
    import time as _time
    devices.make_fake_tree(str(tmp_path), 8)
    out = tmp_path / "rec.jsonl"
    proc = subprocess.Popen(
        [_tfd(native_build), "--interval=0.4", "--conditions",
         "--accelerator=v5e-8", f"--devfs-root={tmp_path}",
         f"--out-file={out}"],
        stderr=subprocess.PIPE)
    try:
        def records():
            if not out.exists():
                return []
            return [json.loads(l) for l in out.read_text().splitlines()]

        deadline = _time.time() + 15
        while len(records()) < 3 and _time.time() < deadline:
            _time.sleep(0.1)
        _time.sleep(1.2)  # ensure the flip lands in a later wall-second
        for i in (5, 6, 7):  # degrade 8 -> 5 chips
            os.unlink(str(tmp_path / "dev" / f"accel{i}"))
        deadline = _time.time() + 15
        while (not any(r["condition"]["status"] == "False"
                       for r in records())
               or records()[-1]["condition"]["status"] != "False"
               or len([r for r in records()
                       if r["condition"]["status"] == "False"]) < 2) \
                and _time.time() < deadline:
            _time.sleep(0.1)
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    recs = records()
    true_recs = [r["condition"] for r in recs if r["condition"]["status"] == "True"]
    false_recs = [r["condition"] for r in recs if r["condition"]["status"] == "False"]
    assert len(true_recs) >= 3 and len(false_recs) >= 2, recs
    # heartbeats advance; transition pinned to the first True cycle
    assert len({c["lastTransitionTime"] for c in true_recs}) == 1
    assert true_recs[0]["lastTransitionTime"] == true_recs[0]["lastHeartbeatTime"]
    # the flip starts a new transition epoch, shared by later False cycles
    assert len({c["lastTransitionTime"] for c in false_recs}) == 1
    assert false_recs[0]["lastTransitionTime"] > true_recs[0]["lastTransitionTime"]
    assert all(c["reason"] == "DegradedChipSet" for c in false_recs)


def test_fake_devices_mode_matches_oracle(native_build, tmp_path):
    """--fake-devices (the kind-e2e census source, mirroring tpud): both
    implementations label present=true with the synthetic chip count."""
    args = ["--print", "--oneshot", "--conditions", "--accelerator=v5e-8",
            "--fake-devices=8"]
    env = {"NODE_NAME": "kind-node"}
    got_cpp = _normalize(_run_record([_tfd(native_build), *args], env))
    got_py = _normalize(_run_record(_python_labeler_cmd(*args), env))
    assert got_cpp == got_py
    assert got_cpp["labels"]["google.com/tpu.present"] == "true"
    assert got_cpp["labels"]["google.com/tpu.count"] == "8"
    assert got_cpp["condition"]["status"] == "True"
