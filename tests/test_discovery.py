"""Device discovery + feature-discovery label tests (fake device tree)."""

import json

from tpu_cluster.discovery import devices, labeler, labels


def test_discover_fake_tree(tmp_path):
    devices.make_fake_tree(str(tmp_path), 8)
    found = devices.discover("/dev/accel*", devfs_root=str(tmp_path))
    assert [d.index for d in found] == list(range(8))
    assert found[3].path.endswith("dev/accel3")
    assert not found[0].vfio


def test_discover_empty(tmp_path):
    assert devices.discover("/dev/accel*", devfs_root=str(tmp_path)) == []


def test_discover_vfio(tmp_path):
    devices.make_fake_tree(str(tmp_path), 4, vfio=True)
    found = devices.discover_vfio(devfs_root=str(tmp_path))
    assert [d.index for d in found] == [0, 1, 2, 3]
    assert all(d.vfio for d in found)


def test_labels_present(tmp_path):
    devices.make_fake_tree(str(tmp_path), 8)
    found = devices.discover("/dev/accel*", devfs_root=str(tmp_path))
    got = labels.compute_labels("v5e-8", found, "node-1")
    assert got == {
        "google.com/tpu.present": "true",
        "google.com/tpu.accelerator-type": "v5e-8",
        "google.com/tpu.generation": "v5e",
        "google.com/tpu.topology": "2x4",
        "google.com/tpu.count": "8",
        "google.com/tpu.ici-domain": "node-1",
    }


def test_labels_absent_deletes_stale_keys():
    got = labels.compute_labels("v5e-8", [])
    assert got["google.com/tpu.present"] == "false"
    # every other key maps to None -> JSON null -> strategic-merge delete
    for key in labels.ALL_KEYS:
        if key != labels.PRESENT:
            assert got[key] is None
    patch = labeler.node_patch(got)
    assert b'"google.com/tpu.count": null' in patch


def test_labeler_fatal_config_errors(tmp_path, capsys):
    # unknown accelerator -> exit 2, not an eternal retry loop
    rc = labeler.main(["--accelerator=v99", "--oneshot", "--print"])
    assert rc == 2
    assert "fatal" in capsys.readouterr().err
    # missing NODE_NAME in patch mode -> exit 2
    import os
    old = os.environ.pop("NODE_NAME", None)
    try:
        rc = labeler.main(["--accelerator=v5e-8", "--oneshot"])
        assert rc == 2
        assert "NODE_NAME" in capsys.readouterr().err
    finally:
        if old is not None:
            os.environ["NODE_NAME"] = old


def test_labeler_oneshot_outfile(tmp_path):
    devices.make_fake_tree(str(tmp_path), 8)
    out = tmp_path / "labels.jsonl"
    rc = labeler.main([
        "--accelerator=v5e-8", f"--devfs-root={tmp_path}",
        "--oneshot", f"--out-file={out}",
    ])
    assert rc == 0
    rec = json.loads(out.read_text().strip())
    assert rec["labels"]["google.com/tpu.present"] == "true"
    assert rec["labels"]["google.com/tpu.count"] == "8"
    assert "condition" not in rec  # --conditions off


def test_tpu_ready_condition_states():
    """TpuReady condition (node-problem-detector analog, SURVEY.md §5)."""
    ok = labeler.tpu_ready_condition("v5e-8", 8)
    assert ok["type"] == "TpuReady" and ok["status"] == "True"
    assert ok["reason"] == "AllChipsPresent"
    degraded = labeler.tpu_ready_condition("v5e-8", 5)
    assert degraded["status"] == "False"
    assert degraded["reason"] == "DegradedChipSet"
    assert "5/8" in degraded["message"]
    none = labeler.tpu_ready_condition("v5e-8", 0)
    assert none["status"] == "False" and none["reason"] == "NoTpuDevices"
    # status patch body merges by condition type
    body = json.loads(labeler.status_patch(ok))
    assert body == {"status": {"conditions": [ok]}}
    # transition time is preserved across same-status heartbeats and reset
    # on a status flip
    first = labeler.tpu_ready_condition("v5e-8", 8, now="T1")
    assert first["lastTransitionTime"] == "T1"
    second = labeler.tpu_ready_condition("v5e-8", 8, now="T2",
                                         previous=first)
    assert second["lastTransitionTime"] == "T1"
    assert second["lastHeartbeatTime"] == "T2"
    flipped = labeler.tpu_ready_condition("v5e-8", 5, now="T3",
                                          previous=second)
    assert flipped["lastTransitionTime"] == "T3"


def test_labeler_conditions_flag(tmp_path, capsys):
    devices.make_fake_tree(str(tmp_path), 8)
    rc = labeler.main([
        "--accelerator=v5e-8", f"--devfs-root={tmp_path}",
        "--oneshot", "--print", "--conditions",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["labels"]["google.com/tpu.present"] == "true"
    assert rec["condition"]["status"] == "True"
    assert rec["condition"]["lastHeartbeatTime"].endswith("Z")
