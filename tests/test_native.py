"""Native component tests: build, selftests, and — the load-bearing part —
gRPC interop between the C++ plugin (grpcmin) and real grpcio peers.

The C++ and Python topology policies are pinned to the same golden file, and
tpud is driven through a real grpcio client exactly as the kubelet's grpc-go
client would drive it (ListAndWatch long-poll, Allocate, preferred
allocation), per the test strategy in SURVEY.md §4.
"""

import json
import os
import shutil
import subprocess
import time
import urllib.request

import grpc
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "data", "topology_golden.json")


# The native_build session fixture lives in conftest.py (shared with the
# feature-discovery oracle tests in test_discovery.py).


def binpath(build, name):
    return os.path.join(build, name)


def start_tpud(build, tmp_path, *extra_args):
    args = [
        binpath(build, "tpud"),
        f"--kubelet-dir={tmp_path}",
        "--endpoint=tpud.sock",
        "--accelerator=v5e-8",
        *extra_args,
    ]
    proc = subprocess.Popen(args, stderr=subprocess.PIPE)
    sock = os.path.join(str(tmp_path), "tpud.sock")
    for _ in range(300):  # up to 15s: loaded 1-core hosts start slowly
        if os.path.exists(sock):
            break
        if proc.poll() is not None:
            raise RuntimeError(
                f"tpud exited rc={proc.returncode}: {proc.stderr.read()}")
        time.sleep(0.05)
    else:
        proc.kill()
        raise RuntimeError("tpud socket never appeared")
    return proc, sock


@pytest.fixture
def tpud_fake8(native_build, tmp_path):
    proc, sock = start_tpud(native_build, tmp_path, "--fake-devices=8",
                            "--no-register")
    yield sock
    proc.terminate()
    proc.wait(timeout=5)


def test_grpcmin_selftest(native_build):
    subprocess.run([binpath(native_build, "grpcmin_selftest")], check=True)


def test_concurrency_stress_selftest(native_build):
    """The threaded hammer over the single-threaded-by-contract layers
    (hpack/h2/minijson + the shared work queue). Plain build here — a
    crash or CHECK failure means actual cross-thread corruption; the
    full data-race detection runs under -DTPU_SANITIZE=thread in CI."""
    out = subprocess.run(
        [binpath(native_build, "concurrency_stress_selftest"),
         "--threads=8", "--rounds=10"],
        check=True, capture_output=True, text=True, timeout=120)
    assert "all OK" in out.stdout
    # the operator's rate-limited workqueue is contention-hammered by the
    # same binary (ISSUE 16) — pin that the phase stays in the source so
    # a refactor cannot silently drop the only multi-threaded coverage
    # the queue gets
    src = open(os.path.join(REPO, "native", "grpcmin",
                            "stress_selftest.cc")).read()
    assert "workqueue::RateLimitedQueue" in src


def test_concurrency_stress_selftest_under_tsan(tmp_path):
    """Build the stress selftest with -fsanitize=thread directly via g++
    and run it — the local twin of the CI TSan job. Skipped when the
    toolchain cannot link libtsan (not installed on every host)."""
    if shutil.which("g++") is None:
        pytest.skip("no g++ on this host")
    from conftest import _GXX_TARGETS  # one source list, no drift
    native = os.path.join(REPO, "native")
    srcs = [os.path.join(native, s)
            for s in _GXX_TARGETS["concurrency_stress_selftest"]]
    binary = os.path.join(tmp_path, "stress_tsan")
    build = subprocess.run(
        ["g++", "-std=c++17", "-O1", "-g", "-fsanitize=thread",
         "-fno-omit-frame-pointer",
         f"-I{native}/operator", f"-I{native}/common",
         f"-I{native}/grpcmin", f"-I{native}/plugin",
         "-o", binary, *srcs, "-pthread"],
        capture_output=True, text=True, timeout=300)
    if build.returncode != 0:
        # only a missing-TSan-runtime toolchain may skip; an actual
        # compile/link error in the sources must FAIL, not skip forever
        err = build.stderr.lower()
        if "tsan" in err and ("cannot find" in err or "no such file" in err
                              or "not found" in err):
            pytest.skip(f"libtsan unavailable: {build.stderr[-200:]}")
        assert False, f"TSan stress build failed:\n{build.stderr[-2000:]}"
    proc = subprocess.run([binary, "--threads=4", "--rounds=5"],
                          capture_output=True, text=True, timeout=300)
    assert "ThreadSanitizer" not in proc.stderr, proc.stderr[-4000:]
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all OK" in proc.stdout


def test_topology_golden_cpp_matches_python(native_build):
    """C++ and Python allocation policies pinned to the same golden file."""
    out = subprocess.run([binpath(native_build, "tpud"),
                          "--print-topology-golden"],
                         check=True, capture_output=True, text=True)
    cpp = json.loads(out.stdout)
    with open(GOLDEN, encoding="utf-8") as f:
        golden = json.load(f)
    cpp_by_name = {a["name"]: a for a in cpp["accelerators"]}
    for entry in golden["accelerators"]:
        got = cpp_by_name[entry["name"]]
        assert got["aligned_sizes"] == entry["aligned_sizes"], entry["name"]
        assert got["aligned_subsets"] == entry["aligned_subsets"], entry["name"]
        assert got["validate_cases"] == entry["validate_cases"], entry["name"]


# ---------------------------------------------------------------- interop


def test_options_and_listandwatch(tpud_fake8):
    from tpu_cluster.plugin_api.client import DevicePluginClient
    c = DevicePluginClient(tpud_fake8)
    try:
        opts = c.get_options()
        assert opts.get_preferred_allocation_available
        stream = c.list_and_watch()
        first = next(stream)
        assert len(first.devices) == 8
        ids = sorted(d.ID for d in first.devices)
        assert ids == [f"tpu-{i}" for i in range(8)]
        assert all(d.health == "Healthy" for d in first.devices)
        stream.cancel()
    finally:
        c.close()


def test_preferred_allocation_interop(tpud_fake8):
    from tpu_cluster.plugin_api.client import DevicePluginClient
    c = DevicePluginClient(tpud_fake8)
    try:
        resp = c.get_preferred_allocation(
            [f"tpu-{i}" for i in range(8)], [], 4)
        got = list(resp.container_responses[0].deviceIDs)
        assert got == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
        # must_include forces the containing quad
        resp = c.get_preferred_allocation(
            [f"tpu-{i}" for i in range(8)], ["tpu-5"], 4)
        got = list(resp.container_responses[0].deviceIDs)
        assert "tpu-5" in got and len(got) == 4
        # fragmented availability -> empty (kubelet falls back)
        resp = c.get_preferred_allocation(
            ["tpu-0", "tpu-3", "tpu-5", "tpu-6"], [], 4)
        assert list(resp.container_responses[0].deviceIDs) == []
    finally:
        c.close()


def test_allocate_aligned(tpud_fake8):
    from tpu_cluster.plugin_api.client import DevicePluginClient
    c = DevicePluginClient(tpud_fake8)
    try:
        resp = c.allocate(["tpu-0", "tpu-1", "tpu-2", "tpu-3"])
        cr = resp.container_responses[0]
        # fake mode is env-only: DeviceSpecs for nodes that don't exist on
        # the host would make runc fail container creation in the kind e2e
        assert list(cr.devices) == []
        assert cr.envs["TPU_VISIBLE_DEVICES"] == "0,1,2,3"
        assert cr.envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
        assert cr.envs["TPU_HOST_BOUNDS"] == "1,1,1"
        assert cr.envs["TPU_SKIP_MDS_QUERY"] == "true"
        assert cr.envs["TPU_ACCELERATOR_TYPE"] == "v5e-8"
        assert cr.envs["TPU_LIBRARY_PATH"] == "/var/lib/tpu/libtpu.so"
        assert cr.mounts[0].host_path == "/var/lib/tpu"
        assert cr.annotations["tpu.native/allocation"] == "0,1,2,3"
        # full host
        resp = c.allocate([f"tpu-{i}" for i in range(8)])
        envs = resp.container_responses[0].envs
        assert envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,4,1"
        assert envs["TPU_VISIBLE_DEVICES"] == "0,1,2,3,4,5,6,7"
    finally:
        c.close()


def test_allocate_unaligned_rejected(tpud_fake8):
    from tpu_cluster.plugin_api.client import DevicePluginClient
    c = DevicePluginClient(tpud_fake8)
    try:
        with pytest.raises(grpc.RpcError) as ei:
            c.allocate(["tpu-0", "tpu-1"])  # size 2 unaligned on v5e-8
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "not aligned" in ei.value.details()
        with pytest.raises(grpc.RpcError) as ei:
            c.allocate(["tpu-0", "tpu-1", "tpu-2", "tpu-4"])  # not a sub-mesh
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "sub-mesh" in ei.value.details()
    finally:
        c.close()


def test_allocate_devfs_tree_device_specs(native_build, tmp_path):
    """Real-device path (devfs-rerooted tree, not fake mode): Allocate
    carries the DeviceSpecs with canonical /dev/accelN container paths and
    rw permissions — the container-toolkit-replacing half of the contract
    (docs/DELTAS.md §2)."""
    from tpu_cluster.discovery import devices as pydev
    from tpu_cluster.plugin_api.client import DevicePluginClient
    devfs = tmp_path / "devfs"
    pydev.make_fake_tree(str(devfs), 8)
    proc, sock = start_tpud(native_build, tmp_path,
                            f"--devfs-root={devfs}", "--no-register")
    c = DevicePluginClient(sock)
    try:
        resp = c.allocate(["tpu-0", "tpu-1", "tpu-2", "tpu-3"])
        cr = resp.container_responses[0]
        assert [d.container_path for d in cr.devices] == [
            f"/dev/accel{i}" for i in range(4)]
        assert [d.host_path for d in cr.devices] == [
            str(devfs / "dev" / f"accel{i}") for i in range(4)]
        assert all(d.permissions == "rw" for d in cr.devices)
        assert cr.envs["TPU_VISIBLE_DEVICES"] == "0,1,2,3"
    finally:
        c.close()
        proc.terminate()
        proc.wait(timeout=5)


def test_prestart_and_unknown_method(tpud_fake8):
    from tpu_cluster.plugin_api import deviceplugin_pb2 as pb
    from tpu_cluster.plugin_api.client import DevicePluginClient
    c = DevicePluginClient(tpud_fake8)
    try:
        c.pre_start_container(["tpu-0"])  # must not raise
        bogus = c.channel.unary_unary(
            "/v1beta1.DevicePlugin/DoesNotExist",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )
        with pytest.raises(grpc.RpcError) as ei:
            bogus(pb.Empty(), timeout=5)
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
    finally:
        c.close()


def test_registration_against_fake_kubelet(native_build, tmp_path):
    """tpud's C++ gRPC client registers with a real grpcio server."""
    from tpu_cluster.plugin_api.fake_kubelet import FakeKubelet
    kubelet = FakeKubelet(os.path.join(str(tmp_path), "kubelet.sock"))
    kubelet.start()
    try:
        proc, _ = start_tpud(native_build, tmp_path, "--fake-devices=8")
        try:
            assert kubelet.wait_for_register(timeout=15)
            req = kubelet.requests[0]
            assert req.version == "v1beta1"
            assert req.endpoint == "tpud.sock"
            assert req.resource_name == "google.com/tpu"
            assert req.options.get_preferred_allocation_available
        finally:
            proc.terminate()
            proc.wait(timeout=5)
    finally:
        kubelet.stop()


def test_device_loss_pushes_listandwatch_update(native_build, tmp_path):
    """Remove a device node -> plugin pushes an updated device list on the
    open ListAndWatch stream (kubelet sees 7 chips)."""
    from tpu_cluster.discovery import devices as pydev
    from tpu_cluster.plugin_api.client import DevicePluginClient
    devfs = tmp_path / "devfs"
    paths = pydev.make_fake_tree(str(devfs), 8)
    proc, sock = start_tpud(
        native_build, tmp_path, f"--devfs-root={devfs}",
        "--rescan-interval=1", "--no-register")
    try:
        c = DevicePluginClient(sock)
        stream = c.list_and_watch()
        first = next(stream)
        assert len(first.devices) == 8
        os.unlink(paths[7])
        second = next(stream)  # pushed within ~1s rescan
        assert len(second.devices) == 7
        assert all(d.ID != "tpu-7" for d in second.devices)
        stream.cancel()
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_reregistration_on_kubelet_restart(native_build, tmp_path):
    """Kubelet restart (socket recreated fast, inode may be reused) and
    plugin-socket wipe must both trigger re-registration — SURVEY.md §7
    hard-part #1 (lifecycle)."""
    from tpu_cluster.plugin_api.fake_kubelet import FakeKubelet
    kubelet = FakeKubelet(os.path.join(str(tmp_path), "kubelet.sock"))
    kubelet.start()
    proc, sock = start_tpud(native_build, tmp_path, "--fake-devices=8")
    try:
        assert kubelet.wait_for_register(timeout=15)
        kubelet.stop()
        k2 = FakeKubelet(os.path.join(str(tmp_path), "kubelet.sock"))
        k2.start()
        try:
            assert k2.wait_for_register(timeout=15), \
                "no re-register after kubelet restart"
            # kubelet wipes the device-plugins dir on restart
            os.unlink(sock)
            k2.event.clear()
            assert k2.wait_for_register(timeout=15), \
                "no re-register after plugin socket wipe"
            assert os.path.exists(sock), "plugin did not re-listen"
        finally:
            k2.stop()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


# ---------------------------------------------------------------- tpu-info


def test_tpu_info_json_and_oneline(native_build, tmp_path):
    from tpu_cluster.discovery import devices as pydev
    pydev.make_fake_tree(str(tmp_path), 8)
    out = subprocess.run(
        [binpath(native_build, "tpu-info"), f"--devfs-root={tmp_path}",
         "--json"],
        check=True, capture_output=True, text=True)
    doc = json.loads(out.stdout)
    assert doc["chip_count"] == 8
    assert doc["accelerator"] == "v5e-8" and doc["topology"] == "2x4"
    one = subprocess.run(
        [binpath(native_build, "tpu-info"), f"--devfs-root={tmp_path}",
         "--oneline"],
        check=True, capture_output=True, text=True)
    assert "8 chip(s)" in one.stdout
    # empty tree -> rc 1 (used as the libtpu-prep readiness signal)
    rc = subprocess.run(
        [binpath(native_build, "tpu-info"),
         f"--devfs-root={tmp_path}/nothing", "--oneline"],
        capture_output=True)
    assert rc.returncode == 1


def test_tpu_info_runtime_metrics(native_build, tmp_path):
    from tpu_cluster.discovery import devices as pydev
    pydev.make_fake_tree(str(tmp_path), 2)
    mf = tmp_path / "metrics.prom"
    mf.write_text('tpu_duty_cycle_percent{chip="0"} 37.5\n'
                  'tpu_tensorcore_utilization_percent{chip="0"} 81.6\n'
                  'tpu_hbm_used_bytes{chip="1"} 1073741824\n')
    out = subprocess.run(
        [binpath(native_build, "tpu-info"), f"--devfs-root={tmp_path}",
         f"--metrics-file={mf}",
         f"--metrics-dir={tmp_path}/no-metrics.d", "--json"],
        check=True, capture_output=True, text=True)
    doc = json.loads(out.stdout)
    assert doc["chips"][0]["duty_cycle_percent"] == 37.5
    assert doc["chips"][0]["tensorcore_utilization_percent"] == 81.6
    assert doc["chips"][1]["hbm_used_bytes"] == 1073741824
    assert "tensorcore_utilization_percent" not in doc["chips"][1]


def test_tpu_info_merges_metrics_drop_dir(native_build, tmp_path):
    """tpu-info reads the same metrics.d union the exporter relays: all
    writers' per-chip gauges merge, stale writers are evicted, and a
    duplicated chip resolves newest-file-wins (round-4 review finding —
    the probe previously read only the legacy file while workloads
    publish into the drop-dir)."""
    import os
    import time as timemod

    from tpu_cluster.discovery import devices as pydev
    pydev.make_fake_tree(str(tmp_path), 4)
    mdir = tmp_path / "metrics.d"
    mdir.mkdir()
    older = mdir / "job-a.prom"
    older.write_text('tpu_duty_cycle_percent{chip="0"} 11\n'
                     'tpu_hbm_used_bytes{chip="1"} 4096\n')
    old = timemod.time() - 60
    os.utime(older, (old, old))
    (mdir / "job-b.prom").write_text(
        'tpu_duty_cycle_percent{chip="0"} 99\n'
        'tpu_hbm_used_bytes{chip="2"} 8192\n')
    dead = mdir / "dead.prom"
    dead.write_text('tpu_duty_cycle_percent{chip="3"} 50\n')
    ancient = timemod.time() - 3600
    os.utime(dead, (ancient, ancient))
    out = subprocess.run(
        [binpath(native_build, "tpu-info"), f"--devfs-root={tmp_path}",
         "--metrics-file=/nonexistent", f"--metrics-dir={mdir}",
         "--stale-after=300", "--json"],
        check=True, capture_output=True, text=True)
    doc = json.loads(out.stdout)
    chips = {c["index"]: c for c in doc["chips"]}
    assert chips[0]["duty_cycle_percent"] == 99      # newest writer wins
    assert chips[1]["hbm_used_bytes"] == 4096        # older writer's chip
    assert chips[2]["hbm_used_bytes"] == 8192        # union across writers
    assert "duty_cycle_percent" not in chips[3]      # stale file evicted


# ---------------------------------------------------------------- exporter


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_ready(port, proc=None, attempts=100):
    """Poll the exporter's /metrics until it serves; fail loudly (with the
    daemon's stderr when available) instead of letting a dead server
    masquerade as the scenario under test."""
    for _ in range(attempts):
        if proc is not None and proc.poll() is not None:
            break
        try:
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=2).read().decode()
        except OSError:
            time.sleep(0.1)
    err = b""
    if proc is not None and proc.poll() is not None and proc.stderr:
        err = proc.stderr.read() or b""
    raise AssertionError(f"exporter never came up: {err.decode()[-500:]}")


def test_exporter_scrape(native_build, tmp_path):
    """BASELINE config 4: metrics scrape returns per-chip HBM/duty-cycle."""
    from tpu_cluster.discovery import devices as pydev
    pydev.make_fake_tree(str(tmp_path), 8)
    mf = tmp_path / "metrics.prom"
    mf.write_text('tpu_duty_cycle_percent{chip="0"} 12.5\n'
                  'not_a_tpu_metric 1\n')
    port = _free_port()
    proc = subprocess.Popen(
        [binpath(native_build, "tpu-metrics-exporter"), f"--port={port}",
         f"--devfs-root={tmp_path}", f"--metrics-file={mf}",
         f"--metrics-dir={tmp_path}/no-metrics.d"],
        stderr=subprocess.PIPE)
    try:
        body = _wait_ready(port, proc)
        assert "tpu_chips_total 8" in body
        assert "tpu_chips_expected 8" in body
        assert 'tpu_chip_present{chip="7"' in body
        assert 'tpu_hbm_capacity_bytes{chip="0"} 17179869184' in body
        assert 'tpu_duty_cycle_percent{chip="0"} 12.5' in body
        assert "not_a_tpu_metric" not in body  # relay filter
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_exporter_split_header_request(native_build, tmp_path):
    """The request head split across TCP segments must still be served:
    the exporter loops its read until \\r\\n\\r\\n (bounded by RCVTIMEO),
    not just the first segment (advisor round-2 weak #5)."""
    import socket as socketmod

    from tpu_cluster.discovery import devices as pydev
    pydev.make_fake_tree(str(tmp_path), 2)
    port = _free_port()
    proc = subprocess.Popen(
        [binpath(native_build, "tpu-metrics-exporter"), f"--port={port}",
         f"--devfs-root={tmp_path}"],
        stderr=subprocess.PIPE)
    try:
        _wait_ready(port, proc)
        with socketmod.create_connection(("127.0.0.1", port), timeout=5) as s:
            for part in (b"GET /met", b"rics HTTP/1.1\r\n",
                         b"Host: localhost\r\n", b"\r\n"):
                s.sendall(part)
                time.sleep(0.05)  # force distinct segments
            body = b""
            while True:
                chunk = s.recv(4096)
                if not chunk:
                    break
                body += chunk
        assert b"200 OK" in body and b"tpu_chips_total 2" in body
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_exporter_status_mode(native_build, tmp_path):
    from tpu_cluster.discovery import devices as pydev
    pydev.make_fake_tree(str(tmp_path), 8)
    libdir = tmp_path / "var" / "lib" / "tpu"
    libdir.mkdir(parents=True)
    (libdir / "libtpu.so").write_bytes(b"\x7fELF-fake")
    port = _free_port()
    proc = subprocess.Popen(
        [binpath(native_build, "tpu-metrics-exporter"), f"--port={port}",
         "--status-mode", f"--devfs-root={tmp_path}",
         "--libtpu-path=/var/lib/tpu/libtpu.so",
         "--plugin-socket=/var/lib/kubelet/device-plugins/tpud.sock",
         "--expect-chips=8"],
        stderr=subprocess.PIPE)
    try:
        doc = None
        for _ in range(50):
            try:
                doc = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=1).read())
                break
            except Exception:
                time.sleep(0.1)
        assert doc is not None
        assert doc["chips"] == 8 and doc["checks"]["chip_count"]
        assert doc["checks"]["libtpu_staged"]
        assert not doc["checks"]["plugin_socket"]  # no socket in fake root
        assert not doc["healthy"]
        # healthz reflects status
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                   timeout=1)
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_allocate_vfio_devices(native_build, tmp_path):
    """VFIO passthrough: host-global IOMMU group numbers (45..48, NOT dense
    chip indices) are re-ranked to chip ids 0..3, group nodes keep their
    /dev/vfio/<group> identity in the container, and the /dev/vfio/vfio
    control node rides along exactly once."""
    from tpu_cluster.plugin_api.client import DevicePluginClient

    vfio_dir = tmp_path / "devfs" / "dev" / "vfio"
    vfio_dir.mkdir(parents=True)
    groups = [45, 46, 47, 48]
    for g in groups:
        (vfio_dir / str(g)).write_text("")
    (vfio_dir / "vfio").write_text("")  # control node
    devfs = tmp_path / "devfs"
    proc, sock = start_tpud(
        native_build, tmp_path, "--accelerator=v5e-4",
        "--device-glob=/dev/vfio/*", f"--devfs-root={devfs}",
        "--no-register")
    c = DevicePluginClient(sock)
    try:
        stream = c.list_and_watch()
        first = next(stream)
        # dense chip ids, not group numbers; control node not advertised
        assert sorted(d.ID for d in first.devices) == [
            f"tpu-{i}" for i in range(4)]
        stream.cancel()

        resp = c.allocate([f"tpu-{i}" for i in range(4)])
        cr = resp.container_responses[0]
        paths = [(d.container_path, d.host_path) for d in cr.devices]
        ctl = [p for p in paths if p[0] == "/dev/vfio/vfio"]
        assert len(ctl) == 1
        assert ctl[0][1] == str(vfio_dir / "vfio")
        grp = [p for p in paths if p[0] != "/dev/vfio/vfio"]
        assert [p[0] for p in grp] == [f"/dev/vfio/{g}" for g in groups]
        assert [p[1] for p in grp] == [str(vfio_dir / str(g))
                                       for g in groups]
        # env stays chip-indexed (the sub-mesh math contract)
        assert cr.envs["TPU_VISIBLE_DEVICES"] == "0,1,2,3"
        assert cr.envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
    finally:
        c.close()
        proc.terminate()
        proc.wait(timeout=5)


def test_tpud_survives_malformed_input(native_build, tmp_path):
    """A device plugin parses whatever connects to its socket; garbage
    (wrong preface, truncated/oversized frames, junk HPACK) must neither
    crash it nor wedge service for well-formed peers."""
    import socket

    from tpu_cluster.plugin_api.client import DevicePluginClient

    proc, sock_path = start_tpud(native_build, tmp_path, "--fake-devices=8",
                                 "--no-register")
    garbage = [
        b"GET / HTTP/1.1\r\nHost: x\r\n\r\n",          # not HTTP/2 at all
        b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n" + b"\xff" * 64,  # preface + junk
        b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
        + b"\x00\x00\x04\x06\x00\x00\x00\x00\x00",      # truncated PING
        b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
        + b"\xff\xff\xff\x00\x00\x00\x00\x00\x01",      # absurd frame length
    ]
    try:
        for payload in garbage:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(5)
            for attempt in range(20):  # accept can lag on a loaded host
                try:
                    s.connect(sock_path)
                    break
                except OSError:
                    if attempt == 19:
                        raise
                    time.sleep(0.25)
            try:
                # tpud may (correctly) slam the connection mid-send on
                # garbage — ECONNRESET here is its defense working, not a
                # failure; the assertions that matter are liveness + service
                s.sendall(payload)
                s.recv(4096)
            except OSError:
                pass
            s.close()
            assert proc.poll() is None, "tpud died on malformed input"
        # well-formed clients still get service afterwards
        c = DevicePluginClient(sock_path)
        try:
            resp = c.allocate(["tpu-0", "tpu-1", "tpu-2", "tpu-3"])
            assert resp.container_responses[0].envs[
                "TPU_VISIBLE_DEVICES"] == "0,1,2,3"
        finally:
            c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_allocate_multihost_slice_env(native_build, tmp_path):
    """v5e-16 (2 hosts x 8): Allocate derives TPU_HOST_BOUNDS from the
    catalogue instead of hardcoding single-host bounds, and sub-host
    requests are rejected (whole-host-group rule for multi-host slices)."""
    from tpu_cluster.plugin_api.client import DevicePluginClient
    proc, sock = start_tpud(native_build, tmp_path, "--fake-devices=8",
                            "--no-register", "--accelerator=v5e-16")
    c = DevicePluginClient(sock)
    try:
        resp = c.allocate([f"tpu-{i}" for i in range(8)])
        envs = resp.container_responses[0].envs
        assert envs["TPU_HOST_BOUNDS"] == "2,1,1"
        assert envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,4,1"
        assert envs["TPU_ACCELERATOR_TYPE"] == "v5e-16"
        with pytest.raises(grpc.RpcError) as ei:
            c.allocate(["tpu-0", "tpu-1", "tpu-2", "tpu-3"])
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "not aligned" in ei.value.details()
    finally:
        c.close()
        proc.terminate()
        proc.wait(timeout=5)


def test_allocate_v5p16_3d_host_bounds(native_build, tmp_path):
    """v5p-16 (2 hosts of flat 2x2 chips stacked along the torus z axis):
    Allocate's TPU_HOST_BOUNDS carries the real z extent "1,1,2" from the
    catalogue — the 3D half of the HOST_BOUNDS contract (round-2 verdict
    next-step #7)."""
    from tpu_cluster.plugin_api.client import DevicePluginClient
    proc, sock = start_tpud(native_build, tmp_path, "--fake-devices=4",
                            "--no-register", "--accelerator=v5p-16")
    c = DevicePluginClient(sock)
    try:
        resp = c.allocate([f"tpu-{i}" for i in range(4)])
        envs = resp.container_responses[0].envs
        assert envs["TPU_HOST_BOUNDS"] == "1,1,2"
        assert envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
        assert envs["TPU_ACCELERATOR_TYPE"] == "v5p-16"
        with pytest.raises(grpc.RpcError) as ei:
            c.allocate(["tpu-0", "tpu-1"])  # sub-host: whole groups only
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        c.close()
        proc.terminate()
        proc.wait(timeout=5)


def test_device_add_pushes_listandwatch_update(native_build, tmp_path):
    """The inverse of hot-unplug: a chip coming (back) online — e.g. a
    repaired node, or libtpu-prep creating nodes late — must be pushed to
    kubelet without a plugin restart, or the node under-advertises until
    the pod is bounced."""
    from tpu_cluster.discovery import devices as pydev
    from tpu_cluster.plugin_api.client import DevicePluginClient
    devfs = tmp_path / "devfs"
    pydev.make_fake_tree(str(devfs), 4)
    proc, sock = start_tpud(
        native_build, tmp_path, f"--devfs-root={devfs}",
        "--rescan-interval=1", "--no-register")
    try:
        c = DevicePluginClient(sock)
        stream = c.list_and_watch()
        first = next(stream)
        assert len(first.devices) == 4
        for i in range(4, 8):
            (devfs / "dev" / f"accel{i}").write_text("")
        second = next(stream)
        assert len(second.devices) == 8
        assert sorted(d.ID for d in second.devices) == [
            f"tpu-{i}" for i in range(8)]
        stream.cancel()
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_exporter_not_wedged_by_silent_client(native_build, tmp_path):
    """A client that connects and sends nothing must not block the
    single-threaded exporter: a concurrent scrape still answers within the
    500ms read-timeout budget."""
    import socket as socketmod

    sock = socketmod.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    proc = subprocess.Popen(
        [binpath(native_build, "tpu-metrics-exporter"), f"--port={port}",
         "--fake-devices=8"], stderr=subprocess.PIPE)
    silent = None
    try:
        _wait_ready(port, proc)
        # park a silent connection, then scrape: must answer despite it
        silent = socketmod.create_connection(("127.0.0.1", port), timeout=5)
        t0 = time.time()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read()
        assert b"tpu_chips_total 8" in body
        assert time.time() - t0 < 5, "scrape stalled behind silent client"
    finally:
        if silent is not None:
            silent.close()
        proc.terminate()
        proc.wait(timeout=10)


def test_exporter_not_wedged_by_drip_feed_client(native_build, tmp_path):
    """A slow-loris client dripping bytes that never complete the request
    head must be cut off by the 2s head deadline (RCVTIMEO alone only
    bounds each read), so a subsequent scrape answers promptly."""
    import socket as socketmod
    import threading

    port = _free_port()
    proc = subprocess.Popen(
        [binpath(native_build, "tpu-metrics-exporter"), f"--port={port}",
         "--fake-devices=8"], stderr=subprocess.PIPE)
    stop = threading.Event()

    def drip():
        try:
            with socketmod.create_connection(
                    ("127.0.0.1", port), timeout=10) as s:
                while not stop.is_set():
                    s.sendall(b"G")  # never reaches \r\n\r\n
                    time.sleep(0.1)
        except OSError:
            pass  # server cut us off — expected

    t = None
    try:
        _wait_ready(port, proc)
        t = threading.Thread(target=drip, daemon=True)
        t.start()
        time.sleep(0.3)  # let the drip occupy the accept loop
        t0 = time.time()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=15).read()
        assert b"tpu_chips_total 8" in body
        # served within the drip client's head deadline plus slack
        assert time.time() - t0 < 6, "scrape stalled behind drip feeder"
    finally:
        stop.set()
        if t is not None:
            t.join(timeout=5)
        proc.terminate()
        proc.wait(timeout=10)


def test_allocate_v5p64_three_axis_host_bounds(native_build, tmp_path):
    """v5p-64 tiles hosts along ALL THREE torus axes (8 hosts of flat 2x2
    chips -> the 4x4x2 torus): TPU_HOST_BOUNDS carries "2,2,2" — no axis
    is degenerate, so any x/y/z ordering bug in the bounds math shows."""
    from tpu_cluster.plugin_api.client import DevicePluginClient
    proc, sock = start_tpud(native_build, tmp_path, "--fake-devices=4",
                            "--no-register", "--accelerator=v5p-64")
    c = DevicePluginClient(sock)
    try:
        resp = c.allocate([f"tpu-{i}" for i in range(4)])
        envs = resp.container_responses[0].envs
        assert envs["TPU_HOST_BOUNDS"] == "2,2,2"
        assert envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
        assert envs["TPU_ACCELERATOR_TYPE"] == "v5p-64"
    finally:
        c.close()
        proc.terminate()
        proc.wait(timeout=5)


def test_tpud_survives_hostile_socket_clients(native_build, tmp_path):
    """Garbage bytes on the plugin's unix socket (a confused prober, a
    half-dead kubelet, port-scanner noise) must not take the daemon down
    or wedge it: a real gRPC client works before, during, and after."""
    import socket as socketmod

    from tpu_cluster.plugin_api.client import DevicePluginClient

    proc, sock = start_tpud(native_build, tmp_path, "--fake-devices=8",
                            "--no-register")
    try:
        c = DevicePluginClient(sock)
        assert len(next(c.list_and_watch()).devices) == 8
        c.close()

        payloads = [
            b"\x00" * 512,                      # nulls
            b"GET / HTTP/1.1\r\nHost: x\r\n\r\n",  # HTTP/1.1 to an h2 port
            b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n" + b"\xff" * 256,  # bad frame
            bytes(range(256)) * 4,              # every byte value
        ]
        for payload in payloads:
            with socketmod.socket(socketmod.AF_UNIX,
                                  socketmod.SOCK_STREAM) as s:
                s.settimeout(2)
                s.connect(sock)
                s.sendall(payload)
                try:  # server may RST or reply (GOAWAY) — both fine
                    s.recv(4096)
                except OSError:
                    pass
            assert proc.poll() is None, "tpud died on hostile bytes"

        # an abruptly-abandoned half-open connection must not wedge the
        # poll loop either
        s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
        s.connect(sock)
        s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")  # preface, then silence

        c = DevicePluginClient(sock)
        assert len(next(c.list_and_watch()).devices) == 8
        resp = c.allocate([f"tpu-{i}" for i in range(8)])
        assert resp.container_responses[0].envs["TPU_ACCELERATOR_TYPE"] \
            == "v5e-8"
        c.close()
        s.close()
        assert proc.poll() is None
    finally:
        proc.terminate()
        proc.wait(timeout=5)
