"""Multi-chip bench line, clusterless (ROADMAP item 5).

Everything here runs on the conftest-forced 8-device CPU virtualmesh:
the flash-crossover selector (pure), the shardbench arm plan and the full
measured path through ``burnin.timed_steps``, the scan-chained collectives
busbw, and the shared bench-entry assembly helper. The crossover constant
is additionally pinned to the measured ledger PROSE it encodes, so the
table and the code path acting on it cannot cite different numbers.
"""

import inspect
import json
import os
import sys
from dataclasses import replace

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
from tpu_cluster.workloads import burnin, collectives, shardbench  # noqa: E402


# ---------------------------------------------------------------- selector

def test_select_attention_flash_iff_tpu_past_crossover():
    cfg = burnin.standard_config()  # d_head = 4096/16 = 256, flash-legal
    at_cross = replace(cfg, seq=burnin.FLASH_CROSSOVER_SEQ)
    below = replace(cfg, seq=burnin.FLASH_CROSSOVER_SEQ // 2)
    assert burnin.select_attention(at_cross, "tpu") == "flash"
    assert burnin.select_attention(
        replace(cfg, seq=2 * burnin.FLASH_CROSSOVER_SEQ), "tpu") == "flash"
    assert burnin.select_attention(below, "tpu") == "xla"
    # never on CPU — the Pallas kernel is Mosaic-compiled, TPU-only
    assert burnin.select_attention(at_cross, "cpu") == "xla"
    assert burnin.select_attention(below, "cpu") == "xla"


def test_select_attention_respects_flash_head_layout():
    # past the crossover but d_head=64 violates the kernel's 128-multiple
    # layout: forward() would raise, so the selector must not pick flash
    cfg = replace(burnin.standard_config(), n_heads=64,
                  seq=burnin.FLASH_CROSSOVER_SEQ)
    assert (cfg.d_model // cfg.n_heads) % 128 != 0
    assert burnin.select_attention(cfg, "tpu") == "xla"


def test_select_attention_chunked_divisibility_guard():
    cfg = replace(burnin.standard_config(), attention="chunked",
                  attn_block=128)
    assert burnin.select_attention(cfg, "tpu") == "chunked"  # 512 % 128 == 0
    ragged = replace(cfg, seq=320)  # 320 % 128 != 0: forward() would raise
    assert burnin.select_attention(ragged, "tpu") == "xla"
    # the crossover outranks an explicit chunked request on TPU
    long = replace(cfg, seq=burnin.FLASH_CROSSOVER_SEQ)
    assert burnin.select_attention(long, "tpu") == "flash"


def test_crossover_constant_cites_the_ledger():
    """The selector's constant and the measured ledger prose
    (standard_config's round-5 long-sequence table) must name the SAME
    seq — re-measuring the crossover has to move both together."""
    src = inspect.getsource(burnin.standard_config)
    s = burnin.FLASH_CROSSOVER_SEQ
    assert f"s{s}/b1:" in src, "ledger row for the crossover seq missing"
    assert f"at s{s}" in src, "ledger conclusion cites a different seq"
    # and the selector actually uses the constant, not a literal copy
    assert "FLASH_CROSSOVER_SEQ" in inspect.getsource(
        burnin.select_attention)


# ---------------------------------------------------------------- make_mesh

def test_make_mesh_error_names_the_offending_axis():
    with pytest.raises(ValueError, match="'data'"):
        burnin.make_mesh((64, 1))  # dp overshoots, tp=1 fits
    with pytest.raises(ValueError, match="'model'"):
        burnin.make_mesh((1, 64))  # tp alone exceeds the device count
    with pytest.raises(ValueError, match="needs 64 devices, have 8"):
        burnin.make_mesh((16, 4))


# ---------------------------------------------------------------- arm plan

def test_plan_arm_shapes_and_batches():
    arms = {a.name: a for a in shardbench.plan(8, tiny=True)}
    assert set(arms) == {"dp", "mp", "long_context"}
    assert arms["dp"].mesh_shape == (8, 1)
    assert arms["mp"].mesh_shape == (2, 4)
    assert arms["long_context"].mesh_shape == (2, 4)
    # global batch scales with the data axis so per-row batch is constant
    base = shardbench._TINY
    assert arms["dp"].cfg.batch == base.batch * 8
    assert arms["mp"].cfg.batch == base.batch * 2
    assert arms["long_context"].cfg.seq > arms["mp"].cfg.seq
    # every batch divides over its data axis (sharding stays whole-shard)
    for a in arms.values():
        assert a.cfg.batch % a.mesh_shape[0] == 0


def test_plan_full_long_context_arm_is_flash_eligible():
    arms = {a.name: a for a in shardbench.plan(8, tiny=False)}
    long = arms["long_context"].cfg
    assert long.seq >= burnin.FLASH_CROSSOVER_SEQ
    assert (long.d_model // long.n_heads) % 128 == 0
    assert burnin.select_attention(long, "tpu") == "flash"
    assert burnin.select_attention(long, "cpu") == "xla"


def test_plan_single_device_degenerates_cleanly():
    for arm in shardbench.plan(1, tiny=True):
        assert arm.mesh_shape == (1, 1)
        assert arm.cfg.batch == shardbench._TINY.batch


# ------------------------------------------------- measured path (8-dev)

def test_run_arms_on_the_virtualmesh():
    """The full sharded bench path, end-to-end and clusterless: every arm
    measured (no errors), spread well-formed, attention labels from the
    selector (xla everywhere — this is CPU), mesh factorisation recorded,
    and the FLOPs denominator scope auditable."""
    doc = shardbench.run_arms(tiny=True)
    assert doc["platform"] == "cpu"
    assert doc["devices"] == 8
    assert set(doc["arms"]) == {"dp", "mp", "long_context"}
    for name, arm in doc["arms"].items():
        assert "error" not in arm, (name, arm)
        assert arm["attention"] == "xla", name  # never flash off-TPU
        assert arm["tflops"] > 0 and arm["tokens_per_s"] > 0, name
        spread = arm.get("tflops_spread")
        if spread is not None:
            assert spread["min"] <= spread["median"] <= spread["max"]
            assert spread["n"] >= 1
        else:  # noise-floor fallback must say so, never silently
            assert "note" in arm, name
        assert arm["flops_scope"] in ("global", "per_device_x8"), name
    assert doc["arms"]["dp"]["mesh"] == {"data": 8, "model": 1}
    assert doc["arms"]["mp"]["mesh"] == {"data": 2, "model": 4}


def test_timed_steps_single_device_scope_is_global():
    """(1,1) meshes must keep the executable FLOPs count untouched — the
    published single-chip rounds depend on that denominator."""
    mesh = burnin.make_mesh((1, 1))
    r = burnin.timed_steps(mesh, shardbench._TINY, steps=2, reps=1)
    assert r["flops_scope"] == "global"
    assert r["flops_per_step"] > 0


def test_run_arms_isolates_a_failing_arm(monkeypatch):
    """One arm failing to compile must not lose the other arms' numbers."""
    real = shardbench.measure_arm

    def boom(arm, platform=None):
        if arm.name == "mp":
            raise RuntimeError("XLA compile failed")
        return real(arm, platform)

    monkeypatch.setattr(shardbench, "measure_arm", boom)
    doc = shardbench.run_arms(tiny=True)
    assert "error" in doc["arms"]["mp"]
    assert "RuntimeError" in doc["arms"]["mp"]["error"]
    assert doc["arms"]["mp"]["mesh"] == {"data": 2, "model": 4}
    assert "error" not in doc["arms"]["dp"]


# ------------------------------------------------------------- collectives

def test_bus_bandwidth_all_reduce_and_all_gather():
    for op in ("all_reduce", "all_gather"):
        r = collectives.bus_bandwidth(op, mib=1, iters=2, reps=2)
        assert r["op"] == op and r["devices"] == 8
        assert r["busbw_gib_s"] > 0
        assert ("busbw_spread" in r) or ("note" in r)


def test_bus_bandwidth_rejects_unknown_op():
    with pytest.raises(ValueError, match="unknown collective op"):
        collectives.bus_bandwidth("all_to_all")


def test_ici_roofline_shape():
    r = collectives.ici_roofline(mib=1, iters=2, reps=2)
    assert r["check"] == "ici_roofline" and r["devices"] == 8
    for op in ("all_reduce", "all_gather"):
        assert r[op]["busbw_gib_s"] > 0
    # CPU virtualmesh: no catalogue ICI peak, so no link_util claim
    assert "link_util" not in r


def test_ici_catalogue_peaks_present():
    from tpu_cluster import topology
    for name in ("v5e-8", "v5p-8", "v6e-8", "v4-8"):
        assert topology.get(name).ici_gbps > 0
    # same generation -> same ICI figure regardless of slice shape
    assert topology.get("v5e-64").ici_gbps == topology.get("v5e-1").ici_gbps


# ---------------------------------------------------- shared entry helper

def test_train_step_entry_assembles_and_rounds():
    ts = {"tflops": 159.987654, "tokens_per_s": 111426.6,
          "points": [{"steps": 40, "seconds": 1.58}],
          "tflops_spread": {"min": 150.0, "median": 160.0, "max": 170.0,
                            "n": 5, "rejected": 0},
          "estimator": "median_of_per_pair_two_point_deltas",
          "flops_scope": "per_device_x8", "attention": "flash"}
    e = bench.train_step_entry("geom", 197.0 * 8, lambda: ts)
    assert e["tflops"] == 159.99
    assert e["mfu"] == round(159.987654 / (197.0 * 8), 3)
    assert e["tokens_per_s"] == 111427
    assert e["attention"] == "flash"
    assert e["flops_scope"] == "per_device_x8"
    assert e["tflops_spread"]["n"] == 5


def test_train_step_entry_no_peak_omits_mfu():
    ts = {"tflops": 0.02, "tokens_per_s": 48123.0, "points": []}
    e = bench.train_step_entry("geom", 0.0, lambda: ts)
    assert "mfu" not in e  # no ratio against nothing (CPU virtualmesh)
    assert e["tflops"] == 0.02


def test_train_step_entry_captures_errors():
    def boom():
        raise RuntimeError("x" * 1000)

    e = bench.train_step_entry("geom", 197.0, boom)
    assert e["config"] == "geom"
    assert len(e["error"]) <= 300 and "RuntimeError" in e["error"]


def test_config_geom_matches_the_published_format():
    """The geom string is what BENCH_r05 rows carry — the extraction must
    reproduce it byte-for-byte or the README rows silently change."""
    assert bench.config_geom(burnin.standard_config()) == (
        "v8192 d4096 f16384 h16 s512 b8 (4x FFN, f32 master)")
    cfg = replace(burnin.standard_config(), param_dtype="bf16",
                  score_dtype="bf16")
    assert bench.config_geom(cfg) == (
        "v8192 d4096 f16384 h16 s512 b8 (4x FFN, bf16 master, bf16 scores)")


def test_shardbench_cli_doc_is_json_serialisable():
    doc = shardbench.run_arms(n_devices=4, tiny=True)
    line = json.dumps(doc)
    assert json.loads(line)["devices"] == 4
