"""Bundle static analyzer tests (tpu_cluster.lint).

Three layers:

- one crafted bad-bundle fixture per rule R01-R06, each asserting the
  rule id AND the JSON-path locus, and that NO other rule fires (the
  rules must be independently testable);
- the self-audit: everything we ship — operand rollout groups, operator
  install waves, validation jobs, the generated chart — must lint clean
  in strict mode, swept over operand-switch x topology permutations of
  valid ClusterSpecs;
- the pre-apply gate: `tpuctl apply --lint=error` against a bad bundle
  exits nonzero with ZERO requests issued to the (fake) apiserver, on
  both the REST and kubectl backends.

Plus the cross-language pins: the linter's operand-workload GVK table is
the Python twin of the C++ operator's drift-watch kind list
(kubeapi::OperandWorkloadKinds — native/operator/selftest.cc pins the
other direction), and the linter's tier model must reproduce
kubeapply._group_tiers exactly.
"""

import json
import os
import re
import sys

import pytest
import yaml

from fake_apiserver import FakeApiServer
from tpu_cluster import kubeapply, lint
from tpu_cluster import spec as specmod
from tpu_cluster import __main__ as cli
from tpu_cluster.render import gotmpl, jobs, manifests, operator_bundle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(REPO, "deploy", "chart", "tpu-stack")
NS = "tpu-system"


# ---------------------------------------------------------------------------
# fixture builders: minimal VALID objects a test then breaks in one way


def mk_namespace(name=NS):
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": name}}


def mk_workload(kind="DaemonSet", name="work", ns=NS, image="img:1",
                labels=None, template_labels=None, pod=None):
    labels = dict(labels or {"app": name})
    api = {"DaemonSet": "apps/v1", "Deployment": "apps/v1",
           "StatefulSet": "apps/v1", "Job": "batch/v1"}[kind]
    pod_spec = {"containers": [{"name": "c", "image": image}]}
    pod_spec.update(pod or {})
    obj = {"apiVersion": api, "kind": kind,
           "metadata": {"name": name, "namespace": ns},
           "spec": {"selector": {"matchLabels": labels},
                    "template": {
                        "metadata": {"labels": dict(template_labels
                                                    if template_labels
                                                    is not None else labels)},
                        "spec": pod_spec}}}
    if kind == "Job":  # Job selectors are controller-generated
        del obj["spec"]["selector"]
    return obj


def mk_configmap(name, ns=NS):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": ns}, "data": {}}


def mk_sa(name, ns=NS):
    return {"apiVersion": "v1", "kind": "ServiceAccount",
            "metadata": {"name": name, "namespace": ns}}


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# one bad-bundle fixture per rule


def test_r01_duplicate_across_groups():
    bundle = [[mk_namespace(), mk_workload(name="dup")],
              [mk_workload(name="dup")]]
    fs = lint.lint_groups(bundle)
    assert rules_of(fs) == {"R01"}
    [f] = fs
    assert f.severity == "error"
    assert (f.kind, f.namespace, f.name) == ("DaemonSet", NS, "dup")
    assert f.path == ".metadata.name"
    assert "group 0" in f.message and "group 1" in f.message


def test_r02_dangling_service_account():
    bundle = [[mk_namespace(),
               mk_workload(pod={"serviceAccountName": "ghost"})]]
    fs = lint.lint_groups(bundle)
    assert rules_of(fs) == {"R02"}
    [f] = fs
    assert f.path == ".spec.template.spec.serviceAccountName"
    assert "ServiceAccount/tpu-system/ghost" in f.message


def test_r02_dangling_configmap_volume_and_envfrom():
    pod = {"volumes": [{"name": "v", "configMap": {"name": "no-such-cm"}}],
           "containers": [{"name": "c", "image": "img:1",
                           "envFrom": [{"secretRef": {"name": "no-such"}}]}]}
    bundle = [[mk_namespace(), mk_workload(pod=pod)]]
    fs = lint.lint_groups(bundle)
    assert rules_of(fs) == {"R02"}
    paths = {f.path for f in fs}
    assert ".spec.template.spec.volumes[0].configMap.name" in paths
    assert (".spec.template.spec.containers[0].envFrom[0].secretRef.name"
            in paths)
    # optional refs are not findings
    pod_opt = {"volumes": [{"name": "v", "configMap": {
        "name": "no-such-cm", "optional": True}}]}
    assert lint.lint_groups([[mk_namespace(),
                              mk_workload(pod=pod_opt)]]) == []


def test_r02_dangling_rolebinding_and_subject():
    binding = {"apiVersion": "rbac.authorization.k8s.io/v1",
               "kind": "ClusterRoleBinding",
               "metadata": {"name": "b"},
               "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                           "kind": "ClusterRole", "name": "ghost-role"},
               "subjects": [{"kind": "ServiceAccount", "name": "ghost-sa",
                             "namespace": NS}]}
    fs = lint.lint_groups([[mk_namespace(), binding]])
    assert rules_of(fs) == {"R02"}
    assert {f.path for f in fs} == {".roleRef.name", ".subjects[0].name"}


def test_r02_service_selector_matches_nothing():
    svc = {"apiVersion": "v1", "kind": "Service",
           "metadata": {"name": "s", "namespace": NS},
           "spec": {"selector": {"app": "nobody"},
                    "ports": [{"port": 80}]}}
    fs = lint.lint_groups([[mk_namespace(), mk_workload(), svc]])
    assert rules_of(fs) == {"R02"}
    [f] = fs
    assert f.kind == "Service" and f.path == ".spec.selector"
    # the same selector pointed at the real workload is clean
    svc_ok = dict(svc, spec={"selector": {"app": "work"},
                             "ports": [{"port": 80}]})
    assert lint.lint_groups([[mk_namespace(), mk_workload(), svc_ok]]) == []


def test_r02_external_allowlist_suppresses():
    bundle = [[mk_namespace(),
               mk_workload(pod={"serviceAccountName": "prometheus"})]]
    assert rules_of(lint.lint_groups(bundle)) == {"R02"}
    ext = set(lint.DEFAULT_EXTERNAL) | {"ServiceAccount/*/prometheus"}
    assert lint.lint_groups(bundle, external=ext) == []


def test_r03_selector_template_mismatch():
    bundle = [[mk_namespace(),
               mk_workload(labels={"app": "x"},
                           template_labels={"app": "y"})]]
    fs = lint.lint_groups(bundle)
    assert rules_of(fs) == {"R03"}
    [f] = fs
    assert f.severity == "error"
    assert f.path == ".spec.selector.matchLabels"
    assert "422" in f.message


def test_r03_match_expressions_only_selector_is_not_flagged():
    """A legal apps/v1 selector using only matchExpressions cannot be
    statically evaluated — the gate must never block a bundle the
    apiserver would accept."""
    obj = mk_workload()
    obj["spec"]["selector"] = {"matchExpressions": [
        {"key": "app", "operator": "In", "values": ["work"]}]}
    assert lint.lint_groups([[mk_namespace(), obj]]) == []


def test_r03_versioned_selector_key_warns_immutable():
    labels = {"app": "w", "app.kubernetes.io/version": "1.2.3"}
    bundle = [[mk_namespace(), mk_workload(labels=labels)]]
    fs = lint.lint_groups(bundle)
    assert rules_of(fs) == {"R03"}
    [f] = fs
    assert f.severity == "warn"
    assert "immutable" in f.message


def test_r04_cr_in_same_group_as_its_crd():
    crd = operator_bundle.crd()
    cr = {"apiVersion": "tpu-stack.dev/v1alpha1", "kind": "TpuStackPolicy",
          "metadata": {"name": "default"}}
    fs = lint.lint_groups([[crd, cr]])
    assert rules_of(fs) == {"R04"}
    [f] = fs
    assert f.path == ".apiVersion" and "Established" in f.message
    # a group boundary between them is the fix
    assert lint.lint_groups([[crd], [cr]]) == []
    # and a CR with no CRD anywhere is also R04 (unless allowlisted)
    fs = lint.lint_groups([[cr]])
    assert rules_of(fs) == {"R04"}
    assert "no matches for kind" in fs[0].message
    assert lint.lint_groups(
        [[cr]], external={"TpuStackPolicy/*"}) == []


def test_r04_namespaced_object_before_its_namespace():
    bundle = [[mk_workload()], [mk_namespace()]]
    fs = lint.lint_groups(bundle)
    assert rules_of(fs) == {"R04"}
    [f] = fs
    assert f.path == ".metadata.namespace"
    assert f.kind == "DaemonSet"


def test_r04_reference_target_in_later_group():
    pod = {"volumes": [{"name": "v", "configMap": {"name": "late-cm"}}]}
    bundle = [[mk_namespace(), mk_workload(pod=pod)],
              [mk_configmap("late-cm")]]
    fs = lint.lint_groups(bundle)
    assert rules_of(fs) == {"R04"}  # in-bundle, so NOT an R02 double-report
    [f] = fs
    assert f.path == ".spec.template.spec.volumes[0].configMap.name"
    # same group is fine: config tier applies before the workload tier
    assert lint.lint_groups([[mk_namespace(), mk_configmap("late-cm"),
                              mk_workload(pod=pod)]]) == []


def test_r05_tpu_request_limit_and_alignment():
    spec = specmod.default_spec()  # v5e-8: aligned sizes 1, 4, 8
    res = {"requests": {"google.com/tpu": "4"},
           "limits": {"google.com/tpu": "8"}}
    job = mk_workload(kind="Job", pod={"containers": [
        {"name": "c", "image": "img:1", "resources": res}]})
    fs = lint.lint_groups([[job]], spec=spec)
    assert rules_of(fs) == {"R05"}
    [f] = fs
    assert f.path == ".spec.template.spec.containers[0].resources"
    assert "request (4) != limit (8)" in f.message

    res_bad = {"requests": {"google.com/tpu": "3"},
               "limits": {"google.com/tpu": "3"}}
    job = mk_workload(kind="Job", pod={"containers": [
        {"name": "c", "image": "img:1", "resources": res_bad}]})
    fs = lint.lint_groups([[job]], spec=spec)
    assert rules_of(fs) == {"R05"}
    assert "not an aligned size for v5e-8" in fs[0].message
    assert "[1, 4, 8]" in fs[0].hint

    res_ok = {"requests": {"google.com/tpu": "4"},
              "limits": {"google.com/tpu": "4"}}
    job = mk_workload(kind="Job", pod={"containers": [
        {"name": "c", "image": "img:1", "resources": res_ok}]})
    assert lint.lint_groups([[job]], spec=spec) == []


def test_r05_host_access_audit_warns_and_allow_annotation():
    pod = {"volumes": [{"name": "h", "hostPath": {"path": "/dev"}}],
           "hostNetwork": True,
           "containers": [{"name": "c", "image": "img:1",
                           "securityContext": {"privileged": True}}]}
    job = mk_workload(kind="Job", pod=pod)
    fs = lint.lint_groups([[job]])
    assert rules_of(fs) == {"R05"}
    assert all(f.severity == "warn" for f in fs)
    assert {f.path for f in fs} == {
        ".spec.template.spec.hostNetwork",
        ".spec.template.spec.volumes[0].hostPath",
        ".spec.template.spec.containers[0].securityContext.privileged"}
    # the scoped acknowledgement waives exactly the named checks...
    job["metadata"]["annotations"] = {
        lint.LINT_ALLOW_ANNOTATION: "hostPath, hostNetwork, privileged"}
    assert lint.lint_groups([[job]]) == []
    # ...but can never waive an error-severity finding
    job["spec"]["template"]["spec"]["containers"][0]["resources"] = {
        "requests": {"google.com/tpu": "1"},
        "limits": {"google.com/tpu": "2"}}
    fs = lint.lint_groups([[job]], spec=specmod.default_spec())
    assert rules_of(fs) == {"R05"}
    assert [f.severity for f in fs] == ["error"]
    # operand workloads — an operand GVK (the C++ drift-watch twin set)
    # that also carries the stack's identity labels — are exempt from the
    # audit: host access is their job ...
    host_pod = {"volumes": [{"name": "h", "hostPath": {"path": "/dev"}}],
                "hostNetwork": True,
                "containers": [{"name": "c", "image": "img:1",
                                "securityContext": {"privileged": True}}]}
    ds = mk_workload(pod=host_pod)
    ds["metadata"]["labels"] = {"app.kubernetes.io/part-of": "tpu-stack"}
    assert lint.lint_groups([[mk_namespace(), ds]]) == []
    # ... but kind alone does not grant the exemption: an arbitrary
    # privileged DaemonSet without the identity labels still warns
    host_pod2 = {"containers": [{"name": "c", "image": "img:1",
                                 "securityContext": {"privileged": True}}]}
    stranger = mk_workload(name="stranger", pod=host_pod2)
    fs = lint.lint_groups([[mk_namespace(), stranger]])
    assert rules_of(fs) == {"R05"} and fs[0].severity == "warn"


def _gang_job(workers, chips, parallelism=None, indexed=True, name="gang"):
    res = {"requests": {"google.com/tpu": str(chips)},
           "limits": {"google.com/tpu": str(chips)}}
    job = mk_workload(kind="Job", name=name, pod={"containers": [
        {"name": "c", "image": "img:1", "resources": res}]})
    job["spec"]["completions"] = workers
    job["spec"]["parallelism"] = (workers if parallelism is None
                                  else parallelism)
    if indexed:
        job["spec"]["completionMode"] = "Indexed"
    return job


def test_r07_worker_count_must_tile_a_catalogue_slice():
    """The deadlock-by-construction bundle: a 3-worker v5e Job matches
    no catalogue slice (v5e tiles 2/4/8 hosts) — its gang can never be
    fully admitted. R07 catches it before any request."""
    spec = specmod.default_spec()  # v5e-8 hosts (2x4, 8 chips)
    fs = lint.lint_groups([[_gang_job(3, 8)]], spec=spec)
    assert rules_of(fs) == {"R07"}
    [f] = fs
    assert f.path == ".spec.completions"
    assert "deadlock by construction" in f.message
    assert "2=v5e-16" in f.message and "4=v5e-32" in f.message
    # 2 workers DO tile v5e-16: clean
    assert lint.lint_groups([[_gang_job(2, 8)]], spec=spec) == []
    # so do 4 (v5e-32) and 8 (v5e-64)
    assert lint.lint_groups([[_gang_job(4, 8)]], spec=spec) == []
    assert lint.lint_groups([[_gang_job(8, 8)]], spec=spec) == []


def test_r07_parallelism_must_equal_completions():
    spec = specmod.default_spec()
    fs = lint.lint_groups([[_gang_job(2, 8, parallelism=1)]], spec=spec)
    assert rules_of(fs) == {"R07"}
    [f] = fs
    assert f.path == ".spec.parallelism"
    assert "every worker running at once" in f.message


def test_r07_multi_worker_needs_whole_host_groups_and_indexed():
    spec = specmod.default_spec()
    # 4 chips/worker on 8-chip hosts: a partially-held host deadlocks
    fs = lint.lint_groups([[_gang_job(2, 4)]], spec=spec)
    assert rules_of(fs) == {"R07"}
    assert "whole host groups" in fs[0].message
    # non-Indexed multi-worker TPU Job: workers cannot rank themselves
    fs = lint.lint_groups([[_gang_job(2, 8, indexed=False)]], spec=spec)
    assert rules_of(fs) == {"R07"}
    assert fs[0].path == ".spec.completionMode"


def test_r07_ignores_single_worker_and_non_tpu_jobs():
    spec = specmod.default_spec()
    # single-worker TPU Job: R05's aligned-size check is the authority
    single = _gang_job(1, 8)
    assert lint.lint_groups([[single]], spec=spec) == []
    # multi-worker Job with no TPU request: none of R07's business
    plain = mk_workload(kind="Job", name="cpu-batch")
    plain["spec"]["completions"] = 3
    plain["spec"]["parallelism"] = 3
    assert lint.lint_groups([[plain]], spec=spec) == []


def test_r07_rendered_multihost_jobs_are_clean():
    """The shipped multi-host validation Jobs (which now opt into gang
    admission via annotations) satisfy their own gate."""
    spec = specmod.load("tpu:\n  accelerator: v5e-16\n")
    groups = [jobs.render_validation_jobs(spec, multihost_hosts=2)]
    assert [f for f in lint.lint_groups(groups, spec=spec)
            if f.rule == "R07"] == []


def test_r06_image_pins():
    for image in ("repo/app", "repo/app:latest"):
        fs = lint.lint_groups([[mk_namespace(), mk_workload(image=image)]])
        assert rules_of(fs) == {"R06"}, image
        [f] = fs
        assert f.severity == "error"
        assert f.path == ".spec.template.spec.containers[0].image"
    # registry ports are not tags; digests are the strongest pin
    for image in ("registry:5000/app:1.2", "repo/app@sha256:" + "0" * 64):
        assert lint.lint_groups(
            [[mk_namespace(), mk_workload(image=image)]]) == [], image


def test_r06_probe_port_cross_check():
    pod = {"containers": [{
        "name": "c", "image": "img:1",
        "ports": [{"name": "http", "containerPort": 80}],
        "readinessProbe": {"httpGet": {"path": "/", "port": "web"}}}]}
    fs = lint.lint_groups([[mk_namespace(),
                            mk_workload(kind="Deployment", pod=pod)]])
    assert rules_of(fs) == {"R06"}
    [f] = fs
    assert f.severity == "error"
    assert f.path == \
        ".spec.template.spec.containers[0].readinessProbe.httpGet.port"
    # numeric-but-undeclared is a warning, not an error
    pod["containers"][0]["readinessProbe"] = {
        "httpGet": {"path": "/", "port": 8080}}
    fs = lint.lint_groups([[mk_namespace(),
                            mk_workload(kind="Deployment", pod=pod)]])
    assert rules_of(fs) == {"R06"} and fs[0].severity == "warn"
    # matching named/numeric probes are clean
    pod["containers"][0]["readinessProbe"] = {
        "httpGet": {"path": "/", "port": "http"}}
    assert lint.lint_groups([[mk_namespace(),
                              mk_workload(kind="Deployment",
                                          pod=pod)]]) == []


# ---------------------------------------------------------------------------
# self-audit: everything we ship lints clean in strict mode


def test_shipped_bundles_lint_clean_strict():
    spec = specmod.default_spec()
    for groups in (manifests.rollout_groups(spec),
                   operator_bundle.operator_install_groups(spec),
                   [jobs.render_validation_jobs(spec, 2)]):
        assert lint.lint_groups(groups, spec=spec) == []


@pytest.mark.parametrize("acc", ["v5e-1", "v5e-4", "v5e-8", "v4-8",
                                 "v5e-16", "v5p-64", "v6e-8"])
def test_lint_of_render_is_clean_for_valid_spec_sweep(acc):
    """Property: lint(render(spec)) == [] for every valid ClusterSpec in
    the sweep (all 32 operand enable combinations x topologies) — the
    renderers may not emit anything the linter objects to, for any spec
    a user can validly write."""
    names = specmod.TpuSpec.OPERAND_NAMES
    for bits in range(2 ** len(names)):
        operands = {name: {"enabled": bool(bits >> i & 1)}
                    for i, name in enumerate(names)}
        spec = specmod.load(yaml.dump(
            {"tpu": {"accelerator": acc, "operands": operands}}))
        for groups in (manifests.rollout_groups(spec),
                       operator_bundle.operator_install_groups(spec)):
            findings = lint.lint_groups(groups, spec=spec)
            assert findings == [], (acc, bits,
                                    [f.line() for f in findings])


def test_generated_chart_lints_clean():
    """scripts/gen_chart.py output through the linter: helm installs
    crds/ before templates render, so the chart lints as [crd] then the
    rendered documents — clean under defaults and with the operator on."""
    with open(os.path.join(CHART, "crds", "tpustackpolicy.yaml"),
              encoding="utf-8") as f:
        crd = yaml.safe_load(f)
    for overrides in ({}, {"operator": {"enabled": True}},
                      {"operator": {"enabled": True},
                       "devicePlugin": {"enabled": False}}):
        docs = gotmpl.render_chart(CHART, overrides)
        findings = lint.lint_groups([[crd], docs],
                                    spec=specmod.default_spec())
        assert findings == [], [f.line() for f in findings]


def test_tier_index_matches_apply_groups_tier_table():
    """The linter's ordering model and the pipelined engine's tier split
    must be the same function — R04 derives from kubeapply's table, so a
    tier change there reshapes lint verdicts here, never silently."""
    group = [mk_namespace(), operator_bundle.crd(), mk_sa("s"),
             mk_configmap("c"), mk_workload(name="d"),
             mk_workload(kind="Deployment", name="dep"),
             mk_workload(kind="Job", name="j")]
    want = [[o for o in group if lint._tier_index(o) == t]
            for t in (0, 1, 2)]
    assert kubeapply._group_tiers(group) == [t for t in want if t]


def test_operand_workload_twin_table_pins_cpp_source():
    """Python half of the twin-table pin (the C++ half lives in
    native/operator/selftest.cc TestOperandWorkloadTwinTable): the kinds
    kubeapi::OperandWorkloadKinds() constructs must equal the linter's
    operand-workload GVK set, verified against the C++ source so the pin
    holds even where no compiler is available."""
    with open(os.path.join(REPO, "native", "operator", "kubeapi.cc"),
              encoding="utf-8") as f:
        src = f.read()
    m = re.search(
        r"OperandWorkloadKinds\(\)\s*\{.*?vector<std::string>\{([^}]*)\}",
        src, re.S)
    assert m, "kubeapi.cc OperandWorkloadKinds() initializer not found"
    cpp_kinds = set(re.findall(r'"([A-Za-z]+)"', m.group(1)))
    assert cpp_kinds == {kind for _, kind in lint.OPERAND_WORKLOAD_KINDS}
    assert {api for api, _ in lint.OPERAND_WORKLOAD_KINDS} == {"apps/v1"}


# ---------------------------------------------------------------------------
# CLI + pre-apply gate


def test_cli_lint_default_bundle_strict_clean(capsys):
    assert cli.main(["lint", "--strict"]) == 0
    assert cli.main(["lint", "--strict", "--operator"]) == 0
    out = capsys.readouterr().out
    assert "0 error(s), 0 warning(s)" in out


def test_cli_lint_reports_findings_and_json(monkeypatch, capsys):
    bad = [[mk_workload(labels={"app": "x"}, template_labels={"app": "y"})]]
    monkeypatch.setattr(cli.manifests, "rollout_groups", lambda spec: bad)
    assert cli.main(["lint"]) == 1
    err = capsys.readouterr().err
    assert "R03" in err and "1 error(s)" in err
    assert cli.main(["lint", "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False and doc["errors"] == 1
    [f] = doc["findings"]
    assert f["rule"] == "R03"
    assert f["path"] == ".spec.selector.matchLabels"


def test_cli_lint_strict_fails_on_warning_only(monkeypatch, capsys):
    warn_only = [[mk_namespace(), mk_workload(
        kind="Job", name="j",
        pod={"volumes": [{"name": "h", "hostPath": {"path": "/x"}}]})]]
    monkeypatch.setattr(cli.manifests, "rollout_groups",
                        lambda spec: warn_only)
    assert cli.main(["lint"]) == 0          # warnings tolerated by default
    assert cli.main(["lint", "--strict"]) == 1
    err = capsys.readouterr().err
    assert "R05" in err


def test_cli_lint_allow_external(monkeypatch):
    bad = [[mk_namespace(),
            mk_workload(pod={"serviceAccountName": "prom"})]]
    monkeypatch.setattr(cli.manifests, "rollout_groups", lambda spec: bad)
    assert cli.main(["lint"]) == 1
    assert cli.main(["lint", "--allow-external",
                     "ServiceAccount/*/prom"]) == 0


def test_apply_lint_error_gate_issues_zero_requests(monkeypatch, capsys):
    """The acceptance pin: `tpuctl apply --lint=error` against a crafted
    bad bundle exits nonzero and the fake apiserver sees NOTHING."""
    bad = [[mk_workload(labels={"app": "x"}, template_labels={"app": "y"})]]
    monkeypatch.setattr(cli.manifests, "rollout_groups", lambda spec: bad)
    with FakeApiServer() as api:
        rc = cli.main(["apply", "--apiserver", api.url, "--lint=error"])
        assert rc == 1
        assert api.log == []  # zero requests reached the apiserver
    out = capsys.readouterr()
    assert "lint gate" in out.err
    assert "R03" in out.out  # the findings were reported before the block


def test_apply_lint_warn_reports_and_proceeds(monkeypatch, capsys):
    bad = [[mk_workload(labels={"app": "x"}, template_labels={"app": "y"})]]
    monkeypatch.setattr(cli.manifests, "rollout_groups", lambda spec: bad)
    with FakeApiServer() as api:  # auto_ready: the rollout converges
        rc = cli.main(["apply", "--apiserver", api.url])  # default: warn
        assert rc == 0
        assert len(api.log) > 0
    out = capsys.readouterr().out
    assert "R03" in out and "proceeding" in out


def test_apply_lint_off_skips_analysis(monkeypatch, capsys):
    bad = [[mk_workload(labels={"app": "x"}, template_labels={"app": "y"})]]
    monkeypatch.setattr(cli.manifests, "rollout_groups", lambda spec: bad)
    with FakeApiServer() as api:
        assert cli.main(["apply", "--apiserver", api.url,
                         "--lint=off"]) == 0
    assert "R03" not in capsys.readouterr().out


def test_apply_allow_external_reaches_the_gate(monkeypatch, capsys):
    """A waiver that satisfies `tpuctl lint --allow-external X` must
    satisfy `apply --lint=error` identically — the allowlist is plumbed
    through both apply backends."""
    bad = [[mk_namespace(),
            mk_workload(pod={"serviceAccountName": "prom"})]]
    monkeypatch.setattr(cli.manifests, "rollout_groups", lambda spec: bad)
    with FakeApiServer() as api:
        assert cli.main(["apply", "--apiserver", api.url,
                         "--lint=error"]) == 1
        assert api.log == []
        assert cli.main(["apply", "--apiserver", api.url, "--lint=error",
                         "--allow-external",
                         "ServiceAccount/*/prom"]) == 0
        assert len(api.log) > 0
    capsys.readouterr()


def test_gate_error_mode_with_warnings_only_proceeds_accurately():
    """error mode with only warn-severity findings proceeds — and the
    log line must say so for the mode actually in force, not claim the
    gate was configured as warn."""
    warn_only = [[mk_namespace(), mk_workload(
        kind="Job", name="j",
        pod={"volumes": [{"name": "h", "hostPath": {"path": "/x"}}]})]]
    msgs = []
    findings = lint.gate(warn_only, "error", log=msgs.append)
    assert [f.severity for f in findings] == ["warn"]
    assert any("--lint=error" in m and "warnings do not block" in m
               for m in msgs)
    assert not any("--lint=warn" in m for m in msgs)


def test_kubectl_backend_gate_blocks_before_first_invocation():
    calls = []

    def runner(argv, input_text=None):
        calls.append(argv)
        return 0, "", ""

    bad = [[mk_workload(labels={"app": "x"}, template_labels={"app": "y"})]]
    with pytest.raises(lint.LintGateError):
        kubeapply.apply_groups_kubectl(bad, wait=False, runner=runner,
                                       lint_mode="error")
    assert calls == []  # zero kubectl invocations


def test_gate_rejects_unknown_mode():
    with pytest.raises(ValueError):
        lint.gate([[mk_namespace()]], "loud")


def test_findings_sort_errors_first():
    bundle = [[mk_namespace(),
               mk_workload(image="repo/app:latest"),  # R06 error
               mk_workload(kind="Job", name="j", pod={
                   "volumes": [{"name": "h",
                                "hostPath": {"path": "/x"}}]})]]  # R05 warn
    fs = lint.lint_groups(bundle)
    assert [f.severity for f in fs] == ["error", "warn"]
    table = lint.format_table(fs)
    assert table.splitlines()[-1] == "lint: 1 error(s), 1 warning(s)"
