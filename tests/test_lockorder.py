"""Lock-order detector tests (tpu_cluster.lockorder).

Two layers:

- seeded-violation units against a PRIVATE monitor (never the global
  one — a deliberately-created cycle must not poison the session graph):
  ABBA cycle detection with the full path named, RLock reentrancy,
  self-deadlock on a non-reentrant re-acquire, Condition integration;
- the regression pin against the GLOBAL monitor conftest installs: a
  full pipelined + shared-watcher + chaos-soak rollout (the satellite's
  "shared watcher + cache_lock interplay"), after which the acquisition
  graph must be cycle-free, the client/telemetry/verify stack must be
  FLAT (zero nesting — the discipline kubeapply keeps on purpose: every
  lock is released before the next is taken), and the fake apiserver
  must show exactly its one known edge (_lock -> _responses_lock, the
  reply-inside-SSA-create path). Any new edge fails the pin and gets
  reviewed before it can deadlock.
"""

import threading
import time

import pytest

from fake_apiserver import (FakeApiServer, soak_seconds,
                            standard_fault_script)
from tpu_cluster import kubeapply, lockorder, telemetry
from tpu_cluster import spec as specmod
from tpu_cluster.render import manifests

FAST_RETRY = kubeapply.RetryPolicy(attempts=8, base_s=0.02, cap_s=0.3)


# ---------------------------------------------------------------- units


def test_abba_cycle_detected_with_path():
    m = lockorder.LockOrderMonitor()
    a = m.make_lock("A")
    b = m.make_lock("B")
    with a:
        with b:
            pass
    assert m.snapshot_violations() == []
    with b:
        with a:
            pass
    violations = m.snapshot_violations()
    assert len(violations) == 1
    assert "cycle" in violations[0]
    assert "A" in violations[0] and "B" in violations[0]
    assert set(m.snapshot_edges()) == {("A", "B"), ("B", "A")}


def test_three_lock_cycle_detected():
    m = lockorder.LockOrderMonitor()
    a, b, c = m.make_lock("A"), m.make_lock("B"), m.make_lock("C")
    for first, second in ((a, b), (b, c)):
        with first:
            with second:
                pass
    assert m.snapshot_violations() == []
    with c:
        with a:
            pass
    violations = m.snapshot_violations()
    assert len(violations) == 1 and "cycle" in violations[0]


def test_rlock_reentry_is_not_a_violation():
    m = lockorder.LockOrderMonitor()
    r = m.make_lock("R", reentrant=True)
    with r:
        with r:
            pass
    assert m.snapshot_violations() == []
    assert m.snapshot_edges() == {}


def test_nonreentrant_self_reacquire_raises_instead_of_hanging():
    m = lockorder.LockOrderMonitor()
    a = m.make_lock("A")
    with a:
        with pytest.raises(RuntimeError, match="self-deadlock"):
            a.acquire()
    assert any("self-deadlock" in v for v in m.snapshot_violations())


def test_timed_reacquire_returns_false_instead_of_raising():
    # acquire(timeout=...) on a held non-reentrant lock is a LEGAL
    # pattern that times out — the monitor must not turn it into a
    # self-deadlock report (only untimed blocking acquires can hang)
    m = lockorder.LockOrderMonitor()
    a = m.make_lock("A")
    with a:
        assert a.acquire(timeout=0.05) is False
    assert m.snapshot_violations() == []
    with a:  # held stack stayed consistent
        pass
    assert m.snapshot_violations() == []


def test_trylock_records_no_ordering():
    # a failed/non-blocking acquire cannot deadlock; it must not
    # constrain the graph
    m = lockorder.LockOrderMonitor()
    a, b = m.make_lock("A"), m.make_lock("B")
    with a:
        assert b.acquire(blocking=False)
        b.release()
    with b:
        with a:
            pass
    # the blocking order b->a is the only edge; no cycle
    assert set(m.snapshot_edges()) == {("B", "A")}
    assert m.snapshot_violations() == []


def test_condition_on_tracked_lock_round_trips():
    m = lockorder.LockOrderMonitor()
    lk = m.make_lock("CVL")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=5)
            hits.append("woken")

    t = threading.Thread(target=waiter)
    t.start()
    with cv:
        hits.append("posted")
        cv.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    assert hits == ["posted", "woken"]
    assert m.snapshot_violations() == []


def test_condition_on_tracked_rlock_waits_correctly():
    """Condition prefers the lock's _is_owned/_release_save/
    _acquire_restore; the proxy must forward them — without that, a
    Condition on a tracked RLock raises 'cannot wait on un-acquired
    lock' (the default _is_owned probe succeeds reentrantly), and a
    doubly-held RLock would be only half-released across wait()."""
    m = lockorder.LockOrderMonitor()
    rl = m.make_lock("RCVL", reentrant=True)
    cv = threading.Condition(rl)
    hits = []

    def waiter():
        with cv:
            with rl:  # doubly held across the wait
                while not hits:
                    cv.wait(timeout=5)
                hits.append("woken")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)  # let the waiter reach wait() holding two levels
    with cv:
        hits.append("posted")
        cv.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    assert hits == ["posted", "woken"]
    assert m.snapshot_violations() == []
    # the main thread's held stack fully drained (restore bookkeeping)
    with rl:
        pass
    assert m.snapshot_violations() == []


def test_release_out_of_order_keeps_held_stack_consistent():
    m = lockorder.LockOrderMonitor()
    a, b = m.make_lock("A"), m.make_lock("B")
    a.acquire()
    b.acquire()
    a.release()  # hand-over-hand: release the outer first
    b.release()
    with b:
        pass
    assert m.snapshot_violations() == []


# ---------------------------------------------------- the regression pin


def _interesting(edges, needles):
    return {(src, dst): site for (src, dst), site in edges.items()
            if any(n in src or n in dst for n in needles)}


def test_soak_graph_is_cycle_free_and_pinned():
    """Drive the full concurrent surface — pipelined engine (cache_lock),
    shared watch readiness (per-wait stats lock + watcher threads),
    retry accounting, telemetry, chaos faults — then pin the observed
    acquisition graph."""
    monitor = lockorder.installed()
    if monitor is None:
        pytest.skip("lock-order monitor disabled (TPU_LOCKORDER=0)")
    spec = specmod.default_spec()
    groups = manifests.rollout_groups(spec)
    tel = telemetry.Telemetry()
    with FakeApiServer(auto_ready=True,
                       chaos=standard_fault_script(0.03)) as api:
        client = kubeapply.Client(api.url, retry=FAST_RETRY,
                                  telemetry=tel)
        kubeapply.apply_groups(client, groups, wait=True,
                               stage_timeout=60, poll=0.02,
                               max_inflight=8, watch_ready=True)
        # warm re-apply exercises the cache_lock + _ssa_is_noop path on
        # live state (the shared watcher + cache interplay); with
        # TPU_SOAK_SECONDS set (ISSUE 18) keep re-applying that long —
        # the tier-1 default stays one warm pass
        soak_end = time.monotonic() + soak_seconds(0.0)
        while True:
            kubeapply.apply_groups(client, groups, wait=True,
                                   stage_timeout=60, poll=0.02,
                                   max_inflight=8, watch_ready=True)
            if time.monotonic() >= soak_end:
                break
        client.close()
    tel.metrics.render()  # exporter path under the monitor too

    violations = monitor.snapshot_violations()
    assert violations == [], "\n".join(violations)

    edges = monitor.snapshot_edges()
    # the client/telemetry stack's pinned order: the ONLY lock ever held
    # across another acquisition is the SSA probe lock, which by design
    # (PR 5: one capability probe per client) stays held through the
    # probing request's transport + telemetry work. Everything else is
    # flat — at most one lock at a time. A new edge is a design change
    # to review, and an edge INTO the probe lock would close a cycle.
    flat_files = ("kubeapply.py", "telemetry.py", "verify.py",
                  "lockorder.py", "conlint.py", "admission.py",
                  "informer.py", "muxhttp.py", "events.py", "slo.py",
                  "metricsdb.py", "maintenance.py")
    nested = _interesting(edges, flat_files)
    probe = "kubeapply.py:Client._ssa_probe_lock"
    unexpected = {e: s for e, s in nested.items() if e[0] != probe}
    assert unexpected == {}, \
        f"client-stack lock nesting appeared: {unexpected}"
    allowed_under_probe = {
        "kubeapply.py:Client._conns_lock",      # keep-alive transport
        "kubeapply.py:Client._retry_lock",      # retry accounting
        "telemetry.py:Tracer.lock",             # wire-attempt span
        "telemetry.py:MetricsRegistry._lock",   # counter/histogram family
        "telemetry.py:Counter._lock",
        "telemetry.py:Histogram._lock",
        # the flight recorder rides the same wire-attempt telemetry the
        # probe request already performs under the lock (ISSUE 8: the
        # CLI arms it for every REST apply); its lock is leaf-only —
        # record()/flush() acquire nothing inside it
        "telemetry.py:FlightRecorder._lock",
        # the events recorder (ISSUE 12): a retry of the SSA probe
        # request emits a Retrying event while the probe lock is held
        # (by design — the probe spans its whole round trip), and the
        # recorder's aggregation lock is leaf-only: the decision is
        # made under it, the Event wire attempt happens after release
        "events.py:EventRecorder._lock",
    }
    under_probe = {e[1] for e in nested if e[0] == probe}
    assert under_probe <= allowed_under_probe, \
        f"new locks taken under the SSA probe lock: " \
        f"{under_probe - allowed_under_probe}"
    assert all(e[1] != probe for e in edges), \
        "something acquired the SSA probe lock while holding another " \
        "lock — that direction can close a deadlock cycle"

    # the fake apiserver's single known edge: replying from inside the
    # store lock (the SSA-create path) takes the audit lock second
    fake_edges = _interesting(edges, ("fake_apiserver.py",))
    allowed = {("fake_apiserver.py:FakeApiServer._lock",
                "fake_apiserver.py:FakeApiServer._responses_lock")}
    assert set(fake_edges) <= allowed, f"unexpected fake edges: {fake_edges}"
    assert set(fake_edges) == allowed, \
        "the pinned _lock -> _responses_lock edge never appeared " \
        "(did the SSA create path stop replying under the store lock?)"


def test_admission_lock_stays_leaf_only():
    """The gang-admission loop's lock discipline (ISSUE 10): state under
    ``_lock``, apiserver I/O outside it — so the admission lock never
    holds across a client/telemetry acquisition and contributes ZERO
    outgoing edges to the process graph. (The soak pin's flat_files also
    names admission.py, so a future nesting fails that pin too; this
    test drives the controller explicitly so the edge set is populated
    even when run alone.)"""
    monitor = lockorder.installed()
    if monitor is None:
        pytest.skip("lock-order monitor disabled (TPU_LOCKORDER=0)")
    from tpu_cluster import admission
    tel = telemetry.Telemetry()
    with FakeApiServer(auto_ready=True) as api:
        client = kubeapply.Client(api.url, telemetry=tel)
        for n in ("lk-a", "lk-b"):
            client.apply(admission.node_manifest(n, "v5e-8"))
        client.apply(admission.gang_job_manifest(
            "locky", "v5e-16", "tpu-system"))
        ctrl = admission.AdmissionController(client, "tpu-system",
                                             telemetry=tel)
        ctrl.step()
        api.set_node_ready("lk-b", ready=False)
        ctrl.step()
        api.set_node_ready("lk-b", ready=True)
        ctrl.step()
        client.close()
    edges = monitor.snapshot_edges()
    outgoing = {e: s for e, s in edges.items()
                if "admission.py" in e[0]}
    assert outgoing == {}, \
        f"admission lock held across another acquisition: {outgoing}"


def test_maintenance_lock_stays_leaf_only():
    """The maintenance controller's lock discipline (ISSUE 18): wave
    state under ``_lock``, every node PATCH / state publish / Event
    emission outside it — so the maintenance lock contributes ZERO
    outgoing edges to the process graph. (The soak pin's flat_files
    names maintenance.py too; this drives a full cordon -> drain ->
    upgrade -> uncordon wave explicitly so the edge set is populated
    even when run alone.)"""
    monitor = lockorder.installed()
    if monitor is None:
        pytest.skip("lock-order monitor disabled (TPU_LOCKORDER=0)")
    from tpu_cluster import admission, maintenance
    tel = telemetry.Telemetry()
    with FakeApiServer(auto_ready=True) as api:
        client = kubeapply.Client(api.url, telemetry=tel)
        for n in ("lk-m-a", "lk-m-b"):
            client.apply(admission.node_manifest(n, "v5e-8"))
        plan = maintenance.plan_waves(
            [admission.HostCapacity(n, "v5e-8", 8, True)
             for n in ("lk-m-a", "lk-m-b")], "v9", group_size=1)
        ctrl = maintenance.MaintenanceController(client, "tpu-system",
                                                 plan=plan, telemetry=tel)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if ctrl.step().complete:
                break
        assert ctrl.state_snapshot().complete
        client.close()
    edges = monitor.snapshot_edges()
    outgoing = {e: s for e, s in edges.items()
                if "maintenance.py" in e[0]}
    assert outgoing == {}, \
        f"maintenance lock held across another acquisition: {outgoing}"


def test_event_recorder_lock_stays_leaf_only():
    """The events recorder's lock discipline (ISSUE 12): aggregation/
    spam-filter decisions under ``_lock``, the Event wire attempt
    outside it — so the recorder contributes ZERO outgoing edges even
    while emitting from inside retry loops and admission passes. (The
    soak pin's flat_files also names events.py; this drives the
    recorder explicitly — POST, count-bump PATCH, spam drop, failed
    write — so the edge set is populated even when run alone.)"""
    monitor = lockorder.installed()
    if monitor is None:
        pytest.skip("lock-order monitor disabled (TPU_LOCKORDER=0)")
    from tpu_cluster import events
    tel = telemetry.Telemetry()
    cm = {"apiVersion": "v1", "kind": "ConfigMap",
          "metadata": {"name": "lk-ev", "namespace": "tpu-system"}}
    chaos = [{"status": 403, "method": "PATCH", "match": "/events/",
              "count": 1}]
    with FakeApiServer(auto_ready=True, chaos=chaos) as api:
        client = kubeapply.Client(api.url, telemetry=tel)
        rec = events.EventRecorder(client, telemetry=tel, spam_burst=3,
                                   spam_refill_per_s=0.0)
        rec.emit(cm, "LockDrive", "post")
        rec.emit(cm, "LockDrive", "post")  # PATCH bump (403s: fail-open)
        for i in range(4):
            rec.emit(cm, "LockDrive", f"spam {i}")  # last one drops
        client.close()
    assert rec.counts()["failures"] >= 1
    assert rec.counts()["dropped"] >= 1
    edges = monitor.snapshot_edges()
    outgoing = {e: s for e, s in edges.items() if "events.py" in e[0]}
    assert outgoing == {}, \
        f"events recorder lock held across another acquisition: {outgoing}"


def test_metricsdb_locks_stay_leaf_only():
    """The scrape pipeline's lock discipline (ISSUE 13): TSDB._lock
    guards the series map, ScrapeManager._lock guards scrape
    accounting, and BOTH are leaf-only — every wire attempt, parse,
    cross-object ingest and telemetry emission happens outside them —
    so a scrape loop feeding a live dashboard contributes ZERO
    outgoing metricsdb edges. (The soak pin's flat_files names
    metricsdb.py too; this drives scrape → ingest → query →
    live-SLO → dash explicitly so the edge set is populated even when
    run alone.)"""
    monitor = lockorder.installed()
    if monitor is None:
        pytest.skip("lock-order monitor disabled (TPU_LOCKORDER=0)")
    from tpu_cluster import metricsdb
    tel = telemetry.Telemetry()
    tsdb = metricsdb.TSDB()
    with FakeApiServer(auto_ready=True) as api:
        client = kubeapply.Client(api.url, telemetry=tel)
        client.apply({"apiVersion": "v1", "kind": "ConfigMap",
                      "metadata": {"name": "lk-mdb",
                                   "namespace": "default"}})
        server = metricsdb.MetricsServer(tel.metrics, 0).start()
        manager = metricsdb.ScrapeManager(
            [metricsdb.Target("fake", api.url + "/__fake_metrics"),
             metricsdb.Target("self", server.url)],
            tsdb, interval_s=0.02, telemetry=tel)
        try:
            manager.start()
            deadline = time.monotonic() + 10
            while manager.scrapes() < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            client.get("/api/v1/namespaces/default/configmaps/lk-mdb")
            manager.scrape_once()
            # the query layer under the monitor too
            tsdb.rate("fake_apiserver_requests_total", 60.0)
            tsdb.histogram_quantile(
                0.99, telemetry.REQUEST_SECONDS, window_s=60.0)
            metricsdb.live_slo_report(tsdb)
            metricsdb.render_dash(tsdb)
            tsdb.dump()
        finally:
            manager.stop()
            server.stop()
            client.close()
    edges = monitor.snapshot_edges()
    outgoing = {e: s for e, s in edges.items()
                if "metricsdb.py" in e[0]}
    assert outgoing == {}, \
        f"metricsdb lock held across another acquisition: {outgoing}"


def test_site_naming_is_stable_and_meaningful():
    """Creation-site naming is the pin's foundation: a Client's locks
    must land on kubeapply.py:Client.<attr> nodes regardless of line
    drift."""
    monitor = lockorder.installed()
    if monitor is None:
        pytest.skip("lock-order monitor disabled (TPU_LOCKORDER=0)")
    client = kubeapply.Client("http://127.0.0.1:1")
    lock = client._conns_lock
    assert isinstance(lock, lockorder._TrackedLock)
    assert lock.name == "kubeapply.py:Client._conns_lock"
    probe = client._ssa_probe_lock
    assert isinstance(probe, lockorder._TrackedLock)
    assert probe.name == "kubeapply.py:Client._ssa_probe_lock"
    assert probe.reentrant
    client.close()


def test_informer_locks_stay_leaf_only():
    """The fleet informer's lock discipline (ISSUE 11): the cache lock
    (``_lock``/``_cond``) and the connection handoff lock
    (``_conn_lock``) are LEAF-ONLY — every apiserver round trip,
    telemetry emission and consumer ``notify`` happens outside them —
    so a watch-driven admission loop over the cache contributes ZERO
    outgoing informer edges to the process graph. (The soak pin's
    flat_files names informer.py/muxhttp.py too; this drives the full
    sync → event → 410-resume → wake cycle so the edge set is populated
    even when run alone.)"""
    monitor = lockorder.installed()
    if monitor is None:
        pytest.skip("lock-order monitor disabled (TPU_LOCKORDER=0)")
    from fake_apiserver import fleet_store
    from tpu_cluster import admission
    tel = telemetry.Telemetry()
    with FakeApiServer(auto_ready=True,
                       store=fleet_store(40, pods_per_node=0)) as api:
        client = kubeapply.Client(api.url, retry=FAST_RETRY,
                                  telemetry=tel, list_page_limit=20)
        client.apply(admission.gang_job_manifest(
            "lk-fleet", "v5e-16", "tpu-system"))
        ctrl = admission.AdmissionController(client, "tpu-system",
                                             telemetry=tel)
        informers = ctrl.build_informers(page_limit=20)
        try:
            informers.start()
            assert informers.wait_synced(30)
            ctrl.step()
            api.touch("/api/v1/nodes/fleet-0001")  # event path
            api.flap()  # the 410 full-resync path
            deadline = time.monotonic() + 10
            nodes_inf = informers.informers[admission.NODES_PATH]
            while nodes_inf.relists < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            # the 410-resume path must actually have run, or the edge
            # set this test exists to populate was never exercised
            assert nodes_inf.relists == 2, nodes_inf.relists
            ctrl.step()
        finally:
            informers.stop()
            client.close()
    edges = monitor.snapshot_edges()
    outgoing = {e: s for e, s in edges.items()
                if "informer.py" in e[0] or "muxhttp.py" in e[0]}
    assert outgoing == {}, \
        f"informer lock held across another acquisition: {outgoing}"
