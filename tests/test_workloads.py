"""JAX workload tests on the 8-device virtual CPU mesh (conftest.py)."""

import jax
import pytest

from tpu_cluster.workloads import burnin, collectives, multihost, smoke


def test_virtual_mesh_is_8_devices():
    assert jax.device_count() == 8


def test_device_report():
    rep = smoke.device_report()
    assert rep["device_count"] == 8
    assert len(rep["devices"]) == 8
    assert rep["devices"][0]["id"] == 0


def test_vector_add():
    assert smoke.vector_add(1 << 12)["ok"]


def test_matmul_smoke():
    r = smoke.matmul(256, 256, 256, iters=2)
    assert r["ok"] and r["tflops"] > 0


def test_run_suite():
    r = smoke.run_suite(matmul_dim=256)
    assert r["ok"] and r["wall_s"] > 0


def test_psum_check():
    r = collectives.psum_check()
    assert r["ok"] and r["devices"] == 8 and r["expected"] == 28.0


def test_psum_subset():
    assert collectives.psum_check(n_devices=4)["ok"]


def test_collective_matrix():
    r = collectives.collective_matrix()
    assert r["ok"], r


def test_allreduce_bandwidth():
    r = collectives.allreduce_bandwidth(mib=1, iters=2)
    assert r["busbw_gib_s"] > 0


def test_burnin_dp_tp():
    r = burnin.run(mesh_shape=(2, 4), steps=4)
    assert r["ok"], r
    assert r["mesh"] == {"data": 2, "model": 4}


def test_remat_knobs_train_identically():
    """Every remat policy ("none"/"attn"/"dots"/"full") computes the same
    training math — rematerialisation changes what is saved for the bwd
    pass, never the result. Losses after 2 steps must agree across knobs."""
    import jax

    histories = {}
    for remat in ("none", "attn", "dots", "full"):
        cfg = burnin.BurninConfig(vocab=64, d_model=32, d_ff=64, n_heads=2,
                                  seq=8, batch=4, remat=remat)
        mesh = burnin.make_mesh((2, 2))
        step, params, batch = burnin.make_sharded_step(mesh, cfg)
        losses = []
        for _ in range(2):
            params, loss = step(params, batch)
            losses.append(float(loss))
        histories[remat] = losses
        jax.clear_caches()
    # tolerance, not equality: recompute can change XLA fusion/rounding in
    # the bwd pass by an ULP without being semantically different
    ref = histories["none"]
    for remat, losses in histories.items():
        assert all(abs(a - b) < 1e-4 for a, b in zip(losses, ref)), histories


def test_chunked_attention_matches_xla_path():
    """attention="chunked" is the flash online-softmax recurrence in plain
    XLA; with f32 running statistics it must agree with the materialised
    masked-softmax path to float tolerance — forward output AND training
    losses."""
    import jax
    import jax.numpy as jnp
    from dataclasses import replace as dc_replace

    cfg = burnin.BurninConfig(vocab=64, d_model=32, d_ff=64, n_heads=2,
                              seq=16, batch=4, attn_block=8)
    params = burnin.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (cfg.batch, cfg.seq), 0, cfg.vocab)
    ref = burnin.forward(params, tokens, cfg)
    chk = burnin.forward(params, tokens,
                         dc_replace(cfg, attention="chunked"))
    # bf16 activation storage dominates: the two paths round the attention
    # weights at different points (unnormalised vs normalised), so ~1e-2
    # relative noise on few-unit logits is the bf16 floor, not an error
    assert float(jnp.abs(ref - chk).max()) < 5e-2, \
        float(jnp.abs(ref - chk).max())

    for variant in (dc_replace(cfg, attention="chunked"),
                    dc_replace(cfg, attention="chunked", attn_block=16)):
        mesh = burnin.make_mesh((2, 2))
        step, p, batch = burnin.make_sharded_step(mesh, variant)
        p, loss = step(p, batch)
        assert float(loss) > 0 and jnp.isfinite(loss)


def test_bf16_score_storage_close_to_f32():
    """score_dtype="bf16" halves the [B,H,S,S] HBM traffic; the weights
    lose mantissa only (max-subtraction bounds the exponent), so the
    forward output must stay close to the f32-score path and training must
    remain finite/decreasing."""
    import jax
    import jax.numpy as jnp
    from dataclasses import replace as dc_replace

    cfg = burnin.BurninConfig(vocab=64, d_model=32, d_ff=64, n_heads=2,
                              seq=16, batch=4)
    params = burnin.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (cfg.batch, cfg.seq), 0, cfg.vocab)
    ref = burnin.forward(params, tokens, cfg)
    b16 = burnin.forward(params, tokens,
                         dc_replace(cfg, score_dtype="bf16"))
    assert float(jnp.abs(ref - b16).max()) < 5e-2
    r = burnin.run(mesh_shape=(2, 2), steps=4,
                   cfg=dc_replace(cfg, score_dtype="bf16"))
    assert r["ok"], r


def test_unknown_attention_knobs_are_rejected():
    """A typo'd mode must raise, not fall through to the default path —
    that would publish one config's MFU under another's label in the
    bench/tune ablation ledgers."""
    import jax
    import pytest
    from dataclasses import replace as dc_replace

    cfg = burnin.BurninConfig(vocab=64, d_model=32, d_ff=64, n_heads=2,
                              seq=8, batch=2)
    params = burnin.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (cfg.batch, cfg.seq), 0, cfg.vocab)
    for bad in (dc_replace(cfg, attention="chunk"),
                dc_replace(cfg, attention="Chunked"),
                dc_replace(cfg, score_dtype="fp32"),
                dc_replace(cfg, param_dtype="fp16"),
                # these knobs are honored on the xla path ONLY; a silent
                # no-op elsewhere would mislabel the measured config
                dc_replace(cfg, attention="chunked", score_dtype="bf16"),
                dc_replace(cfg, attention="chunked", remat="attn"),
                # chunked needs seq divisible by the KV block
                dc_replace(cfg, attention="chunked", attn_block=3)):
        with pytest.raises(ValueError):
            burnin.forward(params, tokens, bad)
    with pytest.raises(ValueError):
        burnin.init_params(dc_replace(cfg, param_dtype="fp16"),
                           jax.random.PRNGKey(0))


def test_fused_xent_matches_autodiff():
    """The hand-fused cross-entropy backward (softmax - onehot, one
    elementwise pass instead of autodiff's scatter) must be numerically
    identical to the plain autodiff reference — value AND gradient."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (3, 5, 17), jnp.float32) * 3.0
    targets = jax.random.randint(jax.random.PRNGKey(1), (3, 5), 0, 17)

    def reference(logits, targets):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(
            logp, targets[..., None], axis=-1).mean()

    v1, g1 = jax.value_and_grad(burnin.softmax_xent)(logits, targets)
    v2, g2 = jax.value_and_grad(reference)(logits, targets)
    assert abs(float(v1) - float(v2)) < 1e-6
    assert float(jnp.abs(g1 - g2).max()) < 1e-6


def test_burnin_default_mesh():
    # power-of-two sweep (the catalogue's device counts) + the odd cases:
    # TP capped at 4, DP takes the rest, product always equals n
    expected = {1: (1, 1), 2: (1, 2), 4: (1, 4), 8: (2, 4), 16: (4, 4)}
    for n, shape in expected.items():
        assert burnin.default_mesh_shape(n) == shape, n
        assert shape[0] * shape[1] == n
    assert burnin.default_mesh_shape(6) == (3, 2)


def test_multihost_plan_single():
    p = multihost.plan({})
    assert p == {"multihost": False, "num_processes": 1, "process_id": 0}


def test_multihost_plan_indexed_job():
    env = multihost.bootstrap_env(
        1, ["job-0.tpu-job.default.svc", "job-1.tpu-job.default.svc"])
    p = multihost.plan(env)
    assert p["multihost"] and p["num_processes"] == 2 and p["process_id"] == 1
    assert p["coordinator_address"] == "job-0.tpu-job.default.svc:8476"


def test_multihost_job_completion_index_fallback():
    p = multihost.plan({
        "JOB_COMPLETION_INDEX": "3",
        "TPU_WORKER_HOSTNAMES": "a,b,c,d",
    })
    assert p["process_id"] == 3 and p["num_processes"] == 4


def test_multihost_missing_hosts():
    with pytest.raises(RuntimeError):
        multihost.coordinator_address({})


def test_multihost_missing_worker_id_is_diagnosable():
    with pytest.raises(RuntimeError, match="completionMode"):
        multihost.plan({"TPU_WORKER_HOSTNAMES": "a,b"})


def test_timed_steps_measures_train_throughput():
    """bench.py's train-step MFU source: scan-batched steps, single-step
    XLA cost analysis x steps, fetch-synced two-point timing."""
    import numpy as np
    from jax.sharding import Mesh

    import jax
    from tpu_cluster.workloads import burnin

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "model"))
    cfg = burnin.BurninConfig(vocab=64, d_model=32, d_ff=64, n_heads=2,
                              seq=8, batch=4)
    ts = burnin.timed_steps(mesh, cfg, steps=2, reps=1)
    assert ts["flops_per_step"] > 0          # cost analysis produced FLOPs
    assert ts["tflops"] >= 0
    assert [p["steps"] for p in ts["points"]] == [2, 6]
    assert ts["tokens_per_s"] > 0


def test_bf16_master_params_train():
    """param_dtype="bf16" (pure-bf16 weights/grads/update, the bench's
    labeled standard_bf16_params entry) must still converge: precision of
    STORAGE changes, the f32 loss arithmetic does not."""
    from dataclasses import replace

    from tpu_cluster.workloads import burnin

    r = burnin.run(steps=4, cfg=replace(burnin.BurninConfig(),
                                        param_dtype="bf16"))
    assert r["ok"], r
