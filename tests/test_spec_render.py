"""ClusterSpec loading + tier 1/2/3 renderer tests."""

import pytest
import yaml

from tpu_cluster import spec as specmod
from tpu_cluster.render import kubeadm, manifests, nodeprep

EXAMPLE = """
cluster:
  name: demo
  kubernetesVersion: "1.28"
  podCidr: 10.244.0.0/16
  controlPlaneEndpoint:
    source: metadata
    cloud: aws
tpu:
  accelerator: v5e-8
  namespace: tpu-system
  operands:
    metricsExporter: {enabled: true, port: 9400}
    nodeStatusExporter: {enabled: false}
"""


def test_load_example():
    s = specmod.load(EXAMPLE)
    assert s.name == "demo"
    assert s.control_plane.cloud == "aws"
    assert s.tpu.accelerator_type.chips_per_host == 8
    assert not s.tpu.operand("nodeStatusExporter").enabled
    assert s.tpu.operand("metricsExporter").extra["port"] == 9400
    assert s.tpu.operand("devicePlugin").enabled  # default on


def test_load_acronym_and_empty_sections():
    # Kubernetes-canonical acronym spelling and the camelCase spelling both work
    s = specmod.load("cluster: {podCIDR: 10.0.0.0/16}")
    assert s.pod_cidr == "10.0.0.0/16"
    s = specmod.load("cluster: {podCidr: 10.1.0.0/16}")
    assert s.pod_cidr == "10.1.0.0/16"
    # empty sections parse to None; must not TypeError
    s = specmod.load("cluster:\n")
    assert s.name == "tpu-cluster"
    s = specmod.load("tpu:\n")
    assert s.tpu.accelerator == "v5e-8"


def test_load_rejects_unknowns():
    with pytest.raises(specmod.SpecError):
        specmod.load("cluster: {bogusField: 1}")
    with pytest.raises(specmod.SpecError):
        specmod.load("tpu:\n  operands:\n    warpDrive: {enabled: true}")
    with pytest.raises(specmod.SpecError):
        specmod.load("cluster: {podCidr: not-a-cidr}")
    with pytest.raises(specmod.SpecError):
        specmod.load("cluster: {podCidr: garbage/999}")
    # unknown accelerator surfaces as SpecError so the CLI prints a clean
    # `spec error:` line (not a KeyError traceback)
    with pytest.raises(specmod.SpecError, match="unknown accelerator"):
        specmod.load("tpu: {accelerator: v99-1}")
    # nested sections are set programmatically; naming them directly is an
    # error, not a silent overwrite
    with pytest.raises(specmod.SpecError):
        specmod.load("cluster: {controlPlane: {source: static}}")
    with pytest.raises(specmod.SpecError):
        specmod.load("cluster: {tpu: {accelerator: v5e-8}}")
    with pytest.raises(specmod.SpecError):
        specmod.load("tpu: {operands: {devicePlugin: 3}}")


def test_operand_bool_shorthand():
    s = specmod.load("tpu: {operands: {devicePlugin: false, libtpuPrep: true}}")
    assert not s.tpu.operand("devicePlugin").enabled
    assert s.tpu.operand("libtpuPrep").enabled


def test_spec_canonicalizes_gce_accelerator_spelling():
    """A spec written with the GCE spelling must validate AND come out
    canonical: the generated CRD/values-schema enums list catalogue names
    only, so a locally-valid alias left unfolded would be rejected by the
    apiserver's enum for the same field."""
    s = specmod.default_spec()
    s.tpu.accelerator = "v5litepod-8"
    s.validate()
    assert s.tpu.accelerator == "v5e-8"
    assert s.tpu.accelerator_type.name == "v5e-8"


def test_node_prep_renders_reference_phase1():
    """Tier-1 parity with reference README.md:5-36."""
    s = specmod.default_spec()
    script = nodeprep.render_node_prep(s)
    assert "overlay" in script and "br_netfilter" in script
    assert "net.bridge.bridge-nf-call-iptables = 1" in script
    assert "net.ipv4.ip_forward = 1" in script
    assert "SystemdCgroup = false/SystemdCgroup = true" in script
    assert "containerd config default" in script
    pkgs = nodeprep.render_kubeadm_packages(s)
    assert "apt-mark hold kubelet kubeadm kubectl" in pkgs
    assert "v1.28" in pkgs


def test_kubeadm_endpoint_sources():
    s = specmod.default_spec()
    s.control_plane.cloud = "aws"
    snip = kubeadm.endpoint_discovery_snippet(s)
    assert "169.254.169.254" in snip
    s.control_plane.cloud = "gcp"
    snip = kubeadm.endpoint_discovery_snippet(s)
    assert "metadata.google.internal" in snip and "Metadata-Flavor" in snip
    s.control_plane.source = "static"
    s.control_plane.address = "10.0.0.5"
    assert kubeadm.endpoint_discovery_snippet(s) == 'CONTROL_PLANE_IP="10.0.0.5"'


def test_kubeadm_init_script():
    s = specmod.default_spec()
    script = kubeadm.render_init_script(s)
    assert "--pod-network-cidr=10.244.0.0/16" in script
    assert ":6443" in script
    assert "kubeadm token create --print-join-command" in script
    assert s.cni_manifest_url in script


def test_manifests_render_and_parse():
    s = specmod.default_spec()
    docs = list(yaml.safe_load_all(manifests.render_all(s)))
    kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
    names = [n for _, n in kinds]
    assert ("Namespace", "tpu-system") in kinds
    for expected in ("tpu-libtpu-prep", "tpu-device-plugin",
                     "tpu-feature-discovery", "tpu-metrics-exporter",
                     "tpu-node-status-exporter"):
        assert expected in names, expected
    # device plugin mounts the kubelet socket dir and /dev
    dp = next(d for d in docs if d["metadata"]["name"] == "tpu-device-plugin"
              and d["kind"] == "DaemonSet")
    mounts = dp["spec"]["template"]["spec"]["containers"][0]["volumeMounts"]
    paths = {m["mountPath"] for m in mounts}
    assert "/var/lib/kubelet/device-plugins" in paths and "/dev" in paths
    args = dp["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--accelerator=v5e-8" in args
    assert "--resource=google.com/tpu" in args
    # libtpu-prep must no-op (exit 0) on CPU-only nodes, not crash-loop the
    # gated rollout
    prep = next(d for d in docs if d["metadata"]["name"] == "tpu-libtpu-prep")
    init_cmd = prep["spec"]["template"]["spec"]["initContainers"][0]["command"][-1]
    assert "touch /shared/no-tpu; exit 0" in init_cmd
    assert "exit 1" not in init_cmd


def test_status_exporter_mount_follows_libtpu_path():
    s = specmod.load("tpu: {libtpuHostPath: /opt/tpu/libtpu.so}")
    docs = list(yaml.safe_load_all(manifests.render_all(s)))
    st = next(d for d in docs
              if d["metadata"]["name"] == "tpu-node-status-exporter")
    podspec = st["spec"]["template"]["spec"]
    mounts = {m["mountPath"] for m in podspec["containers"][0]["volumeMounts"]}
    assert "/opt/tpu" in mounts
    hostpaths = {v.get("hostPath", {}).get("path") for v in podspec["volumes"]}
    assert "/opt/tpu" in hostpaths


def test_operand_enable_flags():
    """The Helm --set surface analog (reference README.md:104-110)."""
    s = specmod.load("""
tpu:
  operands:
    libtpuPrep: {enabled: false}
    featureDiscovery: {enabled: false}
    metricsExporter: {enabled: false}
    nodeStatusExporter: {enabled: false}
""")
    docs = list(yaml.safe_load_all(manifests.render_all(s)))
    names = [d["metadata"]["name"] for d in docs]
    assert names == ["tpu-system", "tpu-device-plugin"]


def test_rollout_groups_ordered():
    """Rollout order mirrors the operator's dependency order (SURVEY §3.3)."""
    s = specmod.default_spec()
    groups = manifests.rollout_groups(s)
    order = [g[0]["metadata"]["name"] for g in groups]
    assert order == ["tpu-system", "tpu-libtpu-prep", "tpu-device-plugin",
                     "tpu-feature-discovery", "tpu-metrics-exporter"]


def test_extra_args_validated_and_rendered():
    """extraArgs: validated in spec.load, splatted into container args."""
    s = specmod.load(
        "tpu:\n  operands:\n"
        "    devicePlugin: {extraArgs: ['--fake-devices=8']}\n"
        "    metricsExporter: {extraArgs: [--fake-devices=8, 42]}\n")
    # items coerced to str at load time
    assert s.tpu.operand("metricsExporter").extra["extraArgs"] == \
        ["--fake-devices=8", "42"]
    dp = manifests.device_plugin(s)
    assert "--fake-devices=8" in \
        dp["spec"]["template"]["spec"]["containers"][0]["args"]
    me_ds = manifests.metrics_exporter(s)[0]
    args = me_ds["spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[-2:] == ["--fake-devices=8", "42"]

    # scalar (the natural one-flag mistake) is rejected, not char-splatted
    with pytest.raises(specmod.SpecError, match="expected a list"):
        specmod.load("tpu: {operands: {devicePlugin: "
                     "{extraArgs: --fake-devices=8}}}")
    # libtpuPrep runs an inline script; extraArgs there is an error
    with pytest.raises(specmod.SpecError, match="not supported"):
        specmod.load("tpu: {operands: {libtpuPrep: {extraArgs: [-v]}}}")


def test_example_specs_load_and_render():
    import glob, os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    examples = sorted(glob.glob(os.path.join(repo, "examples", "*.yaml")))
    assert len(examples) >= 2
    for path in examples:
        s = specmod.load_file(path)
        text = manifests.render_all(s)
        assert "DaemonSet" in text, path
