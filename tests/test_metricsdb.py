"""Continuous-metrics suite (ISSUE 13): the scrape pipeline, the TSDB,
live SLO evaluation and `tpuctl dash`.

The pins, in module order:

- PARSER: `parse_text(reg.render()).samples == reg.samples()` — the
  render/parse symmetry contract — plus hostile-label fuzz (escaped
  quotes/backslashes/newlines round-trip byte-exact through the real
  renderer), label-free samples, +Inf buckets, junk rejection.
- TSDB: counter-reset handling (a restarted target must never produce
  a negative rate), staleness on instant reads, retention pruning,
  histogram_quantile interpolation, dump/load determinism.
- SCRAPER: ingest + self-metric synthesis against the real fake
  apiserver, and the HARD fail-open pin — 100% of targets down leaves
  the loop healthy, `up 0` everywhere, zero exceptions.
- LIVE SLO: `tpuctl slo check --live` reaches the SAME verdict (rc and
  burning window pairs) as the trace-derived path on one shared
  chaos-soak run — the acceptance criterion — and a sustained 503
  storm exits 1 through the real CLI.
- DASH: `tpuctl dash --once --replay` renders the checked-in golden
  frame byte-exact.
- RESTART: an in-process FakeApiServer restart (stop() + new instance
  on the pinned port) severs live watch streams — a client holding a
  watch across the restart sees its stream DIE now, never a zombie
  handler serving the pre-restart store until window expiry.
"""

import http.client
import io
import json
import os
import random
import sys
import threading
import time
from contextlib import redirect_stderr, redirect_stdout

import pytest

from fake_apiserver import FakeApiServer, standard_fault_script
from tpu_cluster import kubeapply, metricsdb, slo, telemetry
from tpu_cluster import spec as specmod
from tpu_cluster.__main__ import main as cli_main
from tpu_cluster.render import manifests

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")
DASH_TSDB = os.path.join(FIXTURES, "dash_tsdb.json")
DASH_GOLDEN = os.path.join(FIXTURES, "dash_golden.txt")

FAST_RETRY = kubeapply.RetryPolicy(attempts=8, base_s=0.02, cap_s=0.3)

NASTY_LABELS = [
    'plain', 'with "quotes"', "back\\slash", "new\nline",
    'all\\three: "\\\n"', "\\n literal backslash-n", "trailing\\",
    "", "comma,brace{}=equals", "unicode ✓ ✗",
]


# ------------------------------------------------------------------ parser


def _nasty_registry() -> telemetry.MetricsRegistry:
    reg = telemetry.MetricsRegistry()
    for i, value in enumerate(NASTY_LABELS):
        reg.counter("hostile_total", "hostile labels", label=value).inc(
            i + 1)
    reg.counter("bare_total", "no labels at all").inc(7)
    reg.gauge("a_gauge", "negative and fractional").set(-2.75)
    hist = reg.histogram("lat_seconds", "latency",
                         buckets=(0.001, 0.25, 4.0), who='h"i\n\\')
    # 0.1 + 0.2 on purpose: the sum is not binary-representable, so
    # samples() must spell values through render()'s _fmt rounding or
    # the parity pin below compares 0.30000000000000004 against the
    # parsed 0.3 and fails
    for v in (0.0001, 0.1, 0.2, 1.0, 99.0):
        hist.observe(v)
    return reg


def test_parse_render_round_trip_parity_pin():
    """THE symmetry contract: parsing render() output reproduces the
    registry's flat sample set and family types exactly — histograms
    included (cumulative le rows, +Inf, _sum, _count)."""
    reg = _nasty_registry()
    parsed = metricsdb.parse_text(reg.render())
    assert parsed.samples == reg.samples()
    assert parsed.types == reg.family_types()
    # the +Inf bucket row exists and equals the observation count
    inf_rows = [v for (name, pairs), v in parsed.samples.items()
                if name == "lat_seconds_bucket"
                and dict(pairs).get("le") == "+Inf"]
    assert inf_rows == [5.0]


def test_parser_hostile_label_fuzz_seeded():
    """Randomized label values over the full escape alphabet
    round-trip byte-exact through the REAL renderer (seeded — a
    failure reproduces)."""
    rng = random.Random(1337)
    alphabet = 'ab"\\\n{},= \t✓'
    values = ["".join(rng.choice(alphabet) for _ in range(rng.randint(0, 24)))
              for _ in range(200)]
    reg = telemetry.MetricsRegistry()
    for i, value in enumerate(values):
        reg.counter("fuzz_total", "", v=value, i=str(i)).inc()
    parsed = metricsdb.parse_text(reg.render())
    got = {dict(pairs)["i"]: dict(pairs)["v"]
           for (name, pairs) in parsed.samples
           if name == "fuzz_total"}
    assert got == {str(i): v for i, v in enumerate(values)}


def test_escape_unescape_inverse():
    for value in NASTY_LABELS:
        assert telemetry.unescape_label(
            telemetry.escape_label(value)) == value
    # unknown escapes keep their backslash (parser tolerance rule)
    assert telemetry.unescape_label("\\x") == "\\x"


def test_parse_tolerates_comments_and_timestamps_rejects_junk():
    doc = ("# some free comment\n"
           "# TYPE x counter\n"
           "x{a=\"b\"} 4 1700000000\n"  # trailing prom timestamp
           "\n"
           "y 2.5\n")
    parsed = metricsdb.parse_text(doc)
    assert parsed.samples[("x", (("a", "b"),))] == 4.0
    assert parsed.samples[("y", ())] == 2.5
    assert parsed.types == {"x": "counter"}
    for junk in ("{no_name} 1", "x{unterminated=\"v} 1",
                 "x{a=\"b\"}", "x notanumber", "x{a=b} 1"):
        with pytest.raises(ValueError):
            metricsdb.parse_text(junk)


# -------------------------------------------------------------------- tsdb


def _clocked_tsdb(**kwargs):
    clock = [0.0]
    tsdb = metricsdb.TSDB(clock=lambda: clock[0], **kwargs)
    return clock, tsdb


def test_counter_reset_never_negative_rate():
    """A restarted target's counter drops to zero mid-window: increase
    counts the post-reset value, rate stays >= 0 — never a negative
    (the satellite's explicit unit)."""
    clock, tsdb = _clocked_tsdb()
    for ts, v in [(0, 100), (1, 120), (2, 5), (3, 15)]:
        clock[0] = float(ts)
        tsdb.append("c_total", {"job": "x"}, v, mtype="counter")
    inc = tsdb.increase("c_total", 10)
    assert inc == {(("job", "x"),): 35.0}  # 20 + 5(reset) + 10
    rate = tsdb.rate("c_total", 10)
    assert all(v >= 0 for v in rate.values())
    assert rate[(("job", "x"),)] == pytest.approx(35.0 / 3.0)


def test_staleness_hides_dead_series_from_instant_reads():
    clock, tsdb = _clocked_tsdb(staleness_s=5.0)
    tsdb.append("up", {"job": "a"}, 1.0)
    clock[0] = 3.0
    assert tsdb.latest("up", job="a") == {(("job", "a"),): 1.0}
    clock[0] = 6.0
    assert tsdb.latest("up", job="a") == {}  # stale, absent — not 1


def test_retention_prunes_and_ring_is_bounded():
    clock, tsdb = _clocked_tsdb(retention_s=10.0,
                                max_samples_per_series=8)
    scrape = metricsdb.ParsedScrape({("m", ()): 1.0}, {"m": "gauge"}, {})
    for ts in range(30):
        clock[0] = float(ts)
        tsdb.ingest(scrape)
    window = tsdb.window("m", 1000.0)
    samples = window[()]
    assert len(samples) <= 8
    assert all(ts >= 20.0 - 1e-9 for ts, _v in samples)


def test_zero_baseline_counts_series_born_under_observation():
    """A counter series first seen on scrape N (while the target was
    already observed at N-1) was genuinely zero a scrape ago — the
    burst-on-a-new-label-set case the live SLO needs counted."""
    clock, tsdb = _clocked_tsdb()
    counter = {"t": "counter"}
    clock[0] = 1.0
    tsdb.ingest(metricsdb.ParsedScrape({("t", ()): 0.0}, counter, {}))
    clock[0] = 2.0
    tsdb.ingest(metricsdb.ParsedScrape(
        {("t", ()): 0.0, ("t", (("code", "503"),)): 3.0}, counter, {}),
        zero_baseline_ts=1.0)
    inc = tsdb.increase("t", 100.0, code="503")
    assert inc == {(("code", "503"),): 3.0}
    # gauges never get a synthetic zero (it would fabricate motion)
    clock[0] = 3.0
    tsdb.ingest(metricsdb.ParsedScrape(
        {("g", ()): 5.0}, {"g": "gauge"}, {}), zero_baseline_ts=2.0)
    assert tsdb.increase("g", 100.0) == {}


def test_ingest_renames_colliding_source_labels_exported():
    """A target that itself exports a ``job`` label (a registry holding
    ANOTHER scrape manager's self-metrics — the self-monitoring setup)
    must keep its series DISTINCT: the source label is renamed to
    ``exported_job`` (the Prometheus convention), never overwritten —
    overwriting collapsed both series into one ring whose interleaved
    values the reset heuristic misread as counter resets, fabricating
    increases."""
    clock, tsdb = _clocked_tsdb()
    counter = {"t": "counter"}

    def scrape_at(ts, a, b):
        clock[0] = ts
        tsdb.ingest(metricsdb.ParsedScrape(
            {("t", (("job", "fake"),)): a,
             ("t", (("job", "self"),)): b}, counter, {}),
            labels={"job": "self"})

    scrape_at(1.0, 5000.0, 300.0)
    scrape_at(2.0, 5100.0, 310.0)
    inc = tsdb.increase("t", 100.0)
    assert inc == {(("exported_job", "fake"), ("job", "self")): 100.0,
                   (("job", "self"),): 10.0}
    # a matching (non-colliding) source value is NOT renamed
    clock, tsdb2 = _clocked_tsdb()
    clock[0] = 1.0
    tsdb2.ingest(metricsdb.ParsedScrape(
        {("t", (("job", "self"),)): 1.0}, counter, {}),
        labels={"job": "self"})
    assert tsdb2.latest("t", job="self") == {(("job", "self"),): 1.0}


def test_baseline_lookback_is_capped_and_windows_bounded_above():
    """Two discriminations the live SLO's short/long windows depend
    on: (1) the pre-window baseline lookback is capped at staleness_s
    — a burst that happened during a long scrape gap must NOT be
    booked into an arbitrarily narrow later window (a false page);
    (2) a range query anchored in the past never sees samples from
    its future."""
    clock, tsdb = _clocked_tsdb(staleness_s=30.0, retention_s=1000.0)
    for ts, v in [(0.0, 100.0), (300.0, 700.0), (301.0, 705.0)]:
        clock[0] = ts
        tsdb.append("c_total", {}, v, mtype="counter")
    clock[0] = 302.0
    # short window: the t=0 baseline is 297s before the window start —
    # far past staleness — so the 600-count burst is NOT attributed
    assert tsdb.increase("c_total", 5.0) == {(): 5.0}
    # long window covering everything still sees the full increase
    assert tsdb.increase("c_total", 1000.0) == {(): 605.0}
    # (2): a window anchored at t=300 must not include the t=301 sample
    win = tsdb.window("c_total", 10.0, now=300.0)
    assert [v for _ts, v in win[()]] == [700.0]


def test_histogram_quantile_interpolates_and_caps_at_finite():
    clock, tsdb = _clocked_tsdb()
    for le, cum in [("0.1", 10.0), ("0.5", 90.0), ("1", 99.0),
                    ("+Inf", 100.0)]:
        tsdb.append("lat_seconds_bucket", {"le": le}, cum)
    p50 = tsdb.histogram_quantile(0.5, "lat_seconds")
    assert 0.1 < p50 < 0.5
    assert p50 == pytest.approx(0.1 + 0.4 * (50 - 10) / (90 - 10))
    # a rank landing in +Inf answers the highest finite bound
    assert tsdb.histogram_quantile(0.999, "lat_seconds") == 1.0
    assert tsdb.histogram_quantile(0.5, "absent") is None


def test_aggregate_sum_avg_max():
    values = {(("a", "1"),): 2.0, (("a", "2"),): 4.0}
    assert metricsdb.aggregate(values) == 6.0
    assert metricsdb.aggregate(values, "avg") == 3.0
    assert metricsdb.aggregate(values, "max") == 4.0
    assert metricsdb.aggregate({}, "max") == 0.0
    with pytest.raises(ValueError):
        metricsdb.aggregate(values, "median")


def test_dump_load_round_trip_is_deterministic():
    clock, tsdb = _clocked_tsdb(max_samples_per_series=5000)
    for ts in (1.0, 2.0, 3.0):
        clock[0] = ts
        tsdb.append("c_total", {"job": "x"}, ts * 10, mtype="counter")
    doc = json.loads(json.dumps(tsdb.dump()))
    loaded = metricsdb.TSDB.load(doc)
    assert loaded.now() == 3.0  # clock frozen at newest sample
    assert loaded.dump() == tsdb.dump()
    # the ring bound survives the round trip: a replay of a store with
    # a non-default bound must not silently truncate its series
    assert loaded.max_samples_per_series == 5000
    assert loaded.family_type("c_total") == "counter"
    # malformed documents are ValueError (the dash CLI's rc-2 path),
    # NEVER a raw AttributeError/TypeError traceback
    for junk in ({"not": "a dump"}, [], 7,
                 {"series": [{"name": "x", "samples": [[None, 1]]}]},
                 {"series": ["not a series"]}):
        with pytest.raises(ValueError):
            metricsdb.TSDB.load(junk)


# ------------------------------------------------------------------ scrape


def test_scrape_ingests_real_fake_scrape_with_self_metrics():
    tsdb = metricsdb.TSDB()
    tel = telemetry.Telemetry()
    with FakeApiServer(auto_ready=True) as api:
        client = kubeapply.Client(api.url)
        manager = metricsdb.ScrapeManager(
            [metricsdb.Target("fake", api.url + "/__fake_metrics")],
            tsdb, telemetry=tel)
        manager.scrape_once()  # observation starts before traffic
        client.apply({"apiVersion": "v1", "kind": "ConfigMap",
                      "metadata": {"name": "cm", "namespace": "default"}})
        manager.scrape_once()
        client.close()
    assert manager.up_snapshot() == {"fake": True}
    assert metricsdb.aggregate(tsdb.latest("up", job="fake"),
                               "max") == 1.0
    # the audit family landed, job-labeled, and a rate is computable
    assert metricsdb.aggregate(
        tsdb.rate("fake_apiserver_requests_total", 60.0,
                  job="fake")) > 0
    # self-metrics: synthesized into the TSDB and the registry
    assert tsdb.latest(telemetry.SCRAPE_DURATION_SECONDS, job="fake")
    assert metricsdb.aggregate(
        tsdb.latest(telemetry.SCRAPE_SAMPLES_TOTAL, job="fake")) > 0
    rendered = tel.metrics.render()
    assert 'up{job="fake"} 1' in rendered
    assert telemetry.SCRAPE_SAMPLES_TOTAL in rendered


def test_scrape_manager_all_targets_down_stays_fail_open():
    """The acceptance pin: 100% of targets dead (refused port + a
    target whose body is JSON, not exposition) — the loop stays
    healthy, up is 0 for every target, zero exceptions surface."""
    tsdb = metricsdb.TSDB()
    with FakeApiServer(auto_ready=True) as api:
        targets = [
            metricsdb.Target("refused", "http://127.0.0.1:1/metrics"),
            # a live HTTP server whose body is a JSON 404 — reachable
            # but NOT exposition text: still a failed scrape, up 0
            metricsdb.Target("garbled",
                             api.url + "/api/v1/namespaces/x"
                             "/configmaps/none"),
        ]
        manager = metricsdb.ScrapeManager(targets, tsdb,
                                          interval_s=0.02,
                                          timeout_s=0.5)
        manager.start()
        deadline = time.monotonic() + 10
        while manager.scrapes() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert manager.scrapes() >= 3
        assert manager.healthy(), "scrape thread died — not fail-open"
        assert manager.up_snapshot() == {"refused": False,
                                         "garbled": False}
        for job in ("refused", "garbled"):
            ups = tsdb.latest("up", job=job)
            assert ups and metricsdb.aggregate(ups, "max") == 0.0
        manager.stop()
        assert not manager.healthy()


def test_scrape_is_wall_bounded_against_a_stalling_target():
    """A STALLED target (accepts, sends nothing — the PR 9 fault
    class) costs at most the scrape wall, not the stall duration."""
    # chaos never intercepts /__fake_metrics (introspection bypasses
    # it) — stall a REGULAR path and scrape that instead
    chaos = [{"stall": 30.0, "match": "/api/v1/nodes"}]
    tsdb = metricsdb.TSDB()
    with FakeApiServer(auto_ready=True, chaos=chaos) as api:
        manager = metricsdb.ScrapeManager(
            [metricsdb.Target("stalled", api.url + "/api/v1/nodes")],
            tsdb, timeout_s=0.3)
        t0 = time.monotonic()
        result = manager.scrape_once()
        elapsed = time.monotonic() - t0
        manager.stop()
    assert result == {"stalled": False}
    assert elapsed < 5.0, f"scrape blocked {elapsed:.1f}s past its wall"


def test_scrape_survives_colliding_self_metric_family_in_registry():
    """Fail-open extends to the telemetry MIRROR: a caller whose
    registry already owns `up` as a COUNTER (type collision with the
    manager's gauge) must not kill the scrape thread — the TSDB
    synthesis still lands and the loop stays healthy."""
    tel = telemetry.Telemetry()
    tel.counter(telemetry.UP, "squatting the name").inc()
    tsdb = metricsdb.TSDB()
    with FakeApiServer(auto_ready=True) as api:
        manager = metricsdb.ScrapeManager(
            [metricsdb.Target("fake", api.url + "/__fake_metrics")],
            tsdb, interval_s=0.02, telemetry=tel)
        manager.start()
        deadline = time.monotonic() + 10
        while manager.scrapes() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert manager.scrapes() >= 3
        assert manager.healthy(), \
            "a registry type collision killed the scrape thread"
        assert manager.up_snapshot() == {"fake": True}
        assert metricsdb.aggregate(tsdb.latest("up", job="fake"),
                                   "max") == 1.0
        manager.stop()


def test_duplicate_job_names_rejected():
    with pytest.raises(ValueError):
        metricsdb.ScrapeManager(
            [metricsdb.Target("a", "http://127.0.0.1:1/m"),
             metricsdb.Target("a", "http://127.0.0.1:2/m")],
            metricsdb.TSDB())
    with pytest.raises(ValueError):
        metricsdb.parse_target("no-equals-url")
    with pytest.raises(ValueError):
        metricsdb.Target("j", "ftp://nope/metrics")


def test_metrics_server_serves_registry_and_conflicts_raise():
    reg = telemetry.MetricsRegistry()
    reg.counter("served_total", "x", job="self").inc(3)
    server = metricsdb.MetricsServer(reg, 0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=5)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        assert 'served_total{job="self"} 3' in body
        conn.request("GET", "/other")
        assert conn.getresponse().read() and True
        conn.close()
        # the bind-conflict contract: constructing on a taken port
        # raises OSError NOW (callers apply their fail-open policy)
        with pytest.raises(OSError):
            metricsdb.MetricsServer(reg, server.port)
        # and a scrape of the served registry round-trips
        tsdb = metricsdb.TSDB()
        manager = metricsdb.ScrapeManager(
            [metricsdb.Target("self", server.url)], tsdb)
        assert manager.scrape_once() == {"self": True}
        manager.stop()
        assert metricsdb.aggregate(
            tsdb.latest("served_total", job="self")) == 3.0
    finally:
        server.stop()


def test_metrics_server_stop_severs_keepalive_handlers():
    """stop() must kill established keep-alive handler threads, not
    just the listener — the same ThreadingHTTPServer zombie the fake's
    restart fix addresses: a scraper's parked connection must die with
    the server instead of being answered from beyond the grave."""
    reg = telemetry.MetricsRegistry()
    reg.counter("zombie_total", "x").inc()
    server = metricsdb.MetricsServer(reg, 0).start()
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=5)
    conn.request("GET", "/metrics")
    assert conn.getresponse().read()  # keep-alive connection is live
    server.stop()
    served = False
    try:
        conn.request("GET", "/metrics")
        served = conn.getresponse().status == 200
    except (OSError, http.client.HTTPException):
        pass
    conn.close()
    assert not served, "a zombie handler served the stopped registry"


def test_admission_metrics_port_bind_conflict_fails_open():
    """`tpuctl admission --metrics-port` on a TAKEN port (or an
    out-of-range one): warn on stderr, loop runs anyway (rc 0) — the
    satellite's fail-open contract."""
    reg = telemetry.MetricsRegistry()
    squatter = metricsdb.MetricsServer(reg, 0).start()
    try:
        with FakeApiServer(auto_ready=True) as api:
            for port in (str(squatter.port), "99999"):
                out, err = io.StringIO(), io.StringIO()
                with redirect_stdout(out), redirect_stderr(err):
                    rc = cli_main(["admission", "--once", "--no-events",
                                   "--apiserver", api.url,
                                   "--namespace", "tpu-system",
                                   "--metrics-port", port])
                assert rc == 0, (port, out.getvalue(), err.getvalue())
                assert "cannot bind metrics port" in err.getvalue(), port
    finally:
        squatter.stop()


def _free_port() -> int:
    import socket as socketmod
    sock = socketmod.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def test_admission_metrics_port_serves_live_registry():
    """The satellite's serving half: a running admission loop with
    --metrics-port is a first-class scrape target — its live registry
    (admission families included) parses as exposition text and feeds
    the TSDB like any other endpoint."""
    import subprocess
    port = _free_port()
    with FakeApiServer(auto_ready=True) as api:
        client = kubeapply.Client(api.url)
        from tpu_cluster import admission
        client.apply(admission.node_manifest("mp-a", "v5e-8"))
        client.apply(admission.node_manifest("mp-b", "v5e-8"))
        client.apply(admission.gang_job_manifest("mp-g", "v5e-16",
                                                 "tpu-system"))
        client.close()
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_cluster", "admission",
             "--apiserver", api.url, "--namespace", "tpu-system",
             "--interval", "0.1", "--no-events",
             "--metrics-port", str(port)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=REPO)
        try:
            tsdb = metricsdb.TSDB()
            manager = metricsdb.ScrapeManager(
                [metricsdb.Target(
                    "admission",
                    f"http://127.0.0.1:{port}/metrics")],
                tsdb, timeout_s=2.0)
            deadline = time.monotonic() + 60
            admitted = 0.0
            while time.monotonic() < deadline:
                manager.scrape_once()
                admitted = metricsdb.aggregate(tsdb.latest(
                    telemetry.ADMISSIONS_TOTAL, job="admission"))
                if admitted > 0:
                    break
                time.sleep(0.1)
            manager.stop()
            assert admitted > 0, "admission families never scraped"
        finally:
            proc.terminate()
            proc.wait(timeout=10)


# ---------------------------------------------------------------- live slo


def test_live_and_trace_derived_slo_reach_the_same_verdict():
    """THE acceptance criterion: one shared chaos-soak run, judged
    twice — from the client's span tree and from counters scraped off
    the fake's live /__fake_metrics — must burn the SAME window pairs
    and produce the same rc-shaped ok bit."""
    tel = telemetry.Telemetry()
    tsdb = metricsdb.TSDB()
    with FakeApiServer(auto_ready=True,
                       chaos=standard_fault_script(0.05)) as api:
        manager = metricsdb.ScrapeManager(
            [metricsdb.Target("fake", api.url + "/__fake_metrics")],
            tsdb, interval_s=0.03)
        manager.start()
        time.sleep(0.05)  # observation starts before the rollout
        client = kubeapply.Client(api.url, telemetry=tel,
                                  retry=FAST_RETRY)
        kubeapply.apply_groups(
            client, manifests.rollout_groups(specmod.default_spec()),
            wait=True, stage_timeout=60, poll=0.02, max_inflight=8)
        client.close()
        time.sleep(0.1)  # one more scrape past the last request
        manager.stop()

    trace_report = slo.evaluate([tel.chrome_trace()])
    live_report = metricsdb.live_slo_report(tsdb)

    def burning_pairs(report):
        return {(v.slo.name, w.severity) for v in report.verdicts
                for w in v.windows if w.burning}

    assert trace_report.ok == live_report.ok
    assert burning_pairs(trace_report) == burning_pairs(live_report)
    # the soak actually bit: the early 503/drop burst must burn the
    # warn pair on BOTH paths (and only warn — the burst is at the
    # START, so the recent page short-window stays clean)
    assert burning_pairs(live_report) == {("apply-availability",
                                           "warn")}
    # SLOs without a live counter expression stay VISIBLY empty
    live_watch = [v for v in live_report.verdicts
                  if v.slo.name == "watch-uptime"][0]
    assert live_watch.total_samples == 0 and not live_watch.burning


def test_slo_check_live_cli_rc0_clean_rc1_on_503_burst():
    """The CLI contract end-to-end: healthy traffic exits 0; a
    sustained 503 storm exits 1 with apply-availability burning."""
    def run_live(api_url):
        out = io.StringIO()
        with redirect_stdout(out):
            rc = cli_main(["slo", "check", "--live",
                           "--targets",
                           f"fake={api_url}/__fake_metrics",
                           "--duration", "0.6",
                           "--scrape-interval", "0.1", "--json"])
        return rc, json.loads(out.getvalue())

    def drive(client, stop):
        while not stop.is_set():
            client.get("/api/v1/namespaces/default/configmaps/probe")
            time.sleep(0.02)

    with FakeApiServer(auto_ready=True) as api:
        client = kubeapply.Client(api.url)
        stop = threading.Event()
        t = threading.Thread(target=drive, args=(client, stop),
                             daemon=True)
        t.start()
        rc, doc = run_live(api.url)
        stop.set()
        t.join(timeout=10)
        client.close()
    assert rc == 0 and doc["ok"], doc

    with FakeApiServer(auto_ready=True,
                       chaos=[{"status": 503, "match": "/api/"}]) as api:
        client = kubeapply.Client(api.url, retry=kubeapply.NO_RETRY)
        stop = threading.Event()
        t = threading.Thread(target=drive, args=(client, stop),
                             daemon=True)
        t.start()
        rc, doc = run_live(api.url)
        stop.set()
        t.join(timeout=10)
        client.close()
    assert rc == 1 and not doc["ok"], doc
    burning = [s["name"] for s in doc["slos"] if s["burning"]]
    assert burning == ["apply-availability"], doc


def test_slo_check_live_cli_invalid_invocations_rc2():
    assert cli_main(["slo", "check", "--live"]) == 2  # no targets
    assert cli_main(["slo", "check"]) == 2  # neither traces nor live
    assert cli_main(["slo", "check", "--targets", "a=http://x/m"]) == 2
    assert cli_main(["slo", "check", "--live", "--targets",
                     "notaurl"]) == 2


def test_slo_check_live_all_targets_down_notes_and_stays_rc0():
    """Dead targets are data, not errors: the live check notes them on
    stderr and reports 'no samples' healthy (rc 0) instead of
    crashing — the fail-open contract surfaced at the CLI."""
    out, err = io.StringIO(), io.StringIO()
    with redirect_stdout(out), redirect_stderr(err):
        rc = cli_main(["slo", "check", "--live", "--targets",
                       "dead=http://127.0.0.1:1/metrics",
                       "--duration", "0.2",
                       "--scrape-interval", "0.05"])
    assert rc == 0
    assert "target dead is down" in err.getvalue()
    assert "no samples" in out.getvalue()


# -------------------------------------------------------------------- dash


def test_dash_replay_renders_the_golden_frame_byte_exact():
    out = io.StringIO()
    with redirect_stdout(out):
        rc = cli_main(["dash", "--once", "--replay", DASH_TSDB])
    assert rc == 0
    with open(DASH_GOLDEN, encoding="utf-8") as f:
        golden = f.read()
    assert out.getvalue() == golden


def test_dash_live_once_smoke_against_the_fake():
    with FakeApiServer(auto_ready=True) as api:
        client = kubeapply.Client(api.url)
        client.apply({"apiVersion": "v1", "kind": "ConfigMap",
                      "metadata": {"name": "d", "namespace": "default"}})
        out = io.StringIO()
        with redirect_stdout(out):
            rc = cli_main(["dash", "--once", "--interval", "0.1",
                           "--targets",
                           f"fake={api.url}/__fake_metrics"])
        client.close()
    frame = out.getvalue()
    assert rc == 0
    assert "fake" in frame and "UP" in frame
    assert " 1 " in frame.splitlines()[2]  # the fake row is up


def test_dash_invalid_invocations_rc2(tmp_path):
    assert cli_main(["dash", "--once"]) == 2  # no targets, no replay
    # duplicate job names are bad input (rc 2), never a traceback
    assert cli_main(["dash", "--once",
                     "--targets", "a=http://127.0.0.1:1/m",
                     "--targets", "a=http://127.0.0.1:2/m"]) == 2
    bogus = tmp_path / "bogus.json"
    bogus.write_text('{"not": "a dump"}')
    assert cli_main(["dash", "--replay", str(bogus)]) == 2
    # non-object / type-mangled dumps are rc 2 too, never a traceback
    bogus.write_text("[]")
    assert cli_main(["dash", "--replay", str(bogus)]) == 2
    bogus.write_text('{"series": [{"name": "x", '
                     '"samples": [[null, 1]]}]}')
    assert cli_main(["dash", "--replay", str(bogus)]) == 2
    assert cli_main(["dash", "--replay",
                     str(tmp_path / "absent.json")]) == 2


# ----------------------------------------------------------------- restart


def _open_raw_watch(port, path, window_s=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=20)
    conn.request("GET", f"{path}?watch=1&timeoutSeconds={window_s}")
    resp = conn.getresponse()
    assert resp.status == 200
    return conn, resp


def test_restart_severs_zombie_watch_streams():
    """The satellite's pin: an in-process restart (stop(), then a new
    FakeApiServer on the pinned port with a different store) severs
    established watch streams — the old handler thread must NOT keep
    serving the pre-restart store until its 30s window expires, and a
    post-restart read never observes pre-restart state."""
    coll = "/api/v1/namespaces/ns/configmaps"
    pre = {f"{coll}/pre-obj": {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "pre-obj", "namespace": "ns"}}}
    post = {f"{coll}/post-obj": {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "post-obj", "namespace": "ns"}}}
    api = FakeApiServer(auto_ready=True, store=pre).start()
    port = api._server.server_address[1]
    conn, resp = _open_raw_watch(port, coll)
    # a POOLED KEEP-ALIVE client held across the restart too: parked
    # plain handlers used to zombie-serve the old store INDEFINITELY
    # (watch streams at least expired with their window)
    held = kubeapply.Client(api.url)
    code, _ = held.get(f"{coll}/pre-obj")
    assert code == 200
    try:
        api.stop()
        api2 = FakeApiServer(auto_ready=True, port=port,
                             store=post).start()
        try:
            t0 = time.monotonic()
            try:
                line = resp.readline()
            except OSError:
                line = b""
            elapsed = time.monotonic() - t0
            assert elapsed < 5.0, \
                f"zombie watch survived the restart {elapsed:.1f}s"
            assert line == b"", \
                f"zombie watch served post-restart bytes: {line!r}"
            # the HELD client's severed socket stale-retries onto the
            # NEW instance — pre-restart state must be gone even on a
            # connection opened before the restart
            code, _ = held.get(f"{coll}/pre-obj")
            assert code == 404, \
                "a zombie keep-alive handler served the old store"
            # and a fresh client sees ONLY the new store
            client = kubeapply.Client(api2.url)
            code, _ = client.get(f"{coll}/post-obj")
            assert code == 200
            listing = client.list_collection(coll)
            assert set(listing) == {"post-obj"}
            client.close()
        finally:
            api2.stop()
    finally:
        held.close()
        conn.close()


def test_flap_invalidates_streams_promptly_and_serves_current_state():
    """flap() (same-instance restart): the held stream dies NOW —
    in-band ERROR/410 or severed socket, whichever wins the race —
    and a fresh watch + LIST sees only current store state."""
    coll = "/api/v1/namespaces/ns/configmaps"
    store = {f"{coll}/pre-obj": {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "pre-obj", "namespace": "ns"}}}
    with FakeApiServer(auto_ready=True, store=store) as api:
        port = api._server.server_address[1]
        conn, resp = _open_raw_watch(port, coll)
        time.sleep(0.05)
        t0 = time.monotonic()
        api.flap()
        try:
            line = resp.readline()
        except OSError:
            line = b""
        elapsed = time.monotonic() - t0
        conn.close()
        assert elapsed < 2.0, f"stream outlived the flap {elapsed:.1f}s"
        if line:  # the graceful race outcome: one in-band 410
            ev = json.loads(line)
            assert ev["type"] == "ERROR"
            assert ev["object"]["code"] == 410
        client = kubeapply.Client(api.url)
        listing = client.list_collection(coll)
        assert set(listing) == {"pre-obj"}
        client.close()
