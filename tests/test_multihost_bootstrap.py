"""Two real processes through the multi-host bootstrap (SURVEY.md §7
hard-part #4): the exact env the Indexed Job + headless Service render is fed
to two subprocesses; each must come up as one JAX process of a 2-process
cluster via workloads.multihost.initialize()."""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, sys
from tpu_cluster.workloads import multihost
plan = multihost.initialize()
import jax
print(json.dumps({
    "plan": plan,
    "process_index": jax.process_index(),
    "process_count": jax.process_count(),
}))
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_two_workers(argv, attempts=2, timeout=180):
    """Launch two workers with the Indexed-Job env contract; returns
    [(rc, stdout, stderr), ...]. The coordinator port comes from free_port(),
    which can race the rest of the suite — retry with a fresh port when the
    failure smells like a bind conflict."""
    last = None
    for _ in range(attempts):
        port = free_port()
        base_env = {
            **os.environ,
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "PALLAS_AXON_POOL_IPS": "",       # force local CPU backend
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            # what the rendered Indexed Job injects (render/jobs.py): the
            # headless-Service DNS names become localhost in this harness
            "TPU_WORKER_HOSTNAMES": "127.0.0.1,127.0.0.1",
            "TPU_COORDINATOR_PORT": str(port),
        }
        procs = []
        for idx in range(2):
            env = {**base_env, "JOB_COMPLETION_INDEX": str(idx)}
            env.pop("TPU_WORKER_ID", None)
            procs.append(subprocess.Popen(
                argv, env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        results = []
        try:
            for proc in procs:
                out, err = proc.communicate(timeout=timeout)
                results.append((proc.returncode, out, err, port))
        finally:
            # a hung handshake must not leak live workers (and the bound
            # coordinator port) into the rest of the suite
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)
        if all(r[0] == 0 for r in results):
            return results
        last = results
        bind_race = any("address already in use" in r[2].lower()
                        or "eaddrinuse" in r[2].lower() for r in results)
        if not bind_race:
            break  # a real failure, not a port race
    return last


def test_two_process_jax_distributed_bootstrap():
    results = run_two_workers([sys.executable, "-c", WORKER])
    parsed = []
    for idx, (rc, out, err, port) in enumerate(results):
        assert rc == 0, f"worker {idx} failed:\n{err[-2000:]}"
        parsed.append((json.loads(out.splitlines()[-1]), port))

    assert {r["process_index"] for r, _ in parsed} == {0, 1}
    for idx, (r, port) in enumerate(parsed):
        assert r["process_count"] == 2
        assert r["plan"]["multihost"] is True
        assert r["plan"]["num_processes"] == 2
        assert r["plan"]["process_id"] == idx
        assert r["plan"]["coordinator_address"] == f"127.0.0.1:{port}"


def test_two_process_global_psum_via_validate_job():
    """BASELINE config 5, 2-node case, end to end: both workers run the
    SAME entry point the rendered Job uses (validate --mode=psum) and the
    all-reduce spans every device of both processes."""
    results = run_two_workers(
        [sys.executable, "-m", "tpu_cluster.workloads.validate",
         "--mode=psum"])
    for idx, (rc, out, err, _) in enumerate(results):
        assert rc == 0, f"worker {idx} failed:\n{err[-2000:]}"
        doc = json.loads(out[out.index("{"):])
        assert doc["ok"], doc
        # the full collective matrix runs across both processes...
        assert doc["devices"] == 8
        for key in ("psum_ok", "all_gather_ok", "reduce_scatter_ok",
                    "ppermute_ok"):
            assert doc[key] is True, (key, doc)
        # ...plus the dedicated global all-reduce acceptance check
        gp = doc["global_psum"]
        assert gp["ok"] and gp["processes"] == 2
        assert gp["total"] == 28.0  # sum(0..7) across both processes
        assert doc["bootstrap"]["process_id"] == idx


def test_two_process_sharded_train_step():
    """SURVEY.md §2.4(b) beyond psum: the flagship DP x TP train step over a
    mesh spanning two processes — model axis within each process (ICI
    analog), data axis across them (DCN). Both workers run the SAME entry
    point the rendered multi-host burnin Job uses (validate --mode=burnin)."""
    results = run_two_workers(
        [sys.executable, "-m", "tpu_cluster.workloads.validate",
         "--mode=burnin"])
    docs = []
    for idx, (rc, out, err, _) in enumerate(results):
        assert rc == 0, f"worker {idx} failed:\n{err[-2000:]}"
        docs.append(json.loads(out[out.index("{"):]))
    for idx, doc in enumerate(docs):
        assert doc["ok"], doc
        assert doc["processes"] == 2
        assert doc["devices"] == 8          # 2 procs x 4 virtual devices
        # data axis (2) spans the processes; model axis (4) stays local
        assert doc["mesh"] == {"data": 2, "model": 4}
        assert doc["loss_decreasing"] is True
        assert doc["bootstrap"]["process_id"] == idx
    # SPMD: the replicated loss history must be identical on both workers
    assert docs[0]["losses"] == docs[1]["losses"]


def test_two_process_device_query_checks_global_slice():
    """Multi-host device-query must verify the ASSEMBLED slice: per-worker
    local count against the catalogue AND the global device count across
    all workers (a half-joined slice must fail, not pass per-pod)."""
    results = run_two_workers(
        [sys.executable, "-m", "tpu_cluster.workloads.validate",
         "--mode=device-query", "--expect-devices=4"])
    for idx, (rc, out, err, _) in enumerate(results):
        assert rc == 0, f"worker {idx} failed:\n{err[-2000:]}"
        doc = json.loads(out[out.index("{"):])
        assert doc["ok"], doc
        assert doc["local_device_count"] == 4
        assert doc["expected_global_devices"] == 8
        assert doc["global_device_count"] == 8


def test_four_process_sharded_train_step():
    """v5e-32 is a 4-host slice: prove the bootstrap + sharded step at that
    process count, DP axis spanning all four workers over DCN (2 virtual
    devices each), model axis host-local — the same layout the rendered
    4-worker Indexed Job produces."""
    worker = (
        "import json\n"
        "from tpu_cluster.workloads import multihost, burnin\n"
        "plan = multihost.initialize()\n"
        "import jax\n"
        "doc = burnin.run(mesh_shape=(4, 2), steps=3)\n"
        "doc['plan'] = plan\n"
        "print(json.dumps(doc))\n"
    )
    port = free_port()
    base_env = {
        **os.environ,
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "TPU_WORKER_HOSTNAMES": ",".join(["127.0.0.1"] * 4),
        "TPU_COORDINATOR_PORT": str(port),
    }
    procs = []
    for idx in range(4):
        env = {**base_env, "JOB_COMPLETION_INDEX": str(idx)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", worker], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = []
    try:
        for proc in procs:
            out, err = proc.communicate(timeout=240)
            results.append((proc.returncode, out, err))
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
    docs = []
    for idx, (rc, out, err) in enumerate(results):
        assert rc == 0, f"worker {idx} failed:\n{err[-2000:]}"
        docs.append(json.loads(out.splitlines()[-1]))
    for doc in docs:
        assert doc["ok"], doc
        assert doc["processes"] == 4
        assert doc["devices"] == 8
        assert doc["mesh"] == {"data": 4, "model": 2}
    assert len({tuple(d["losses"]) for d in docs}) == 1  # SPMD agreement
