"""Two real processes through the multi-host bootstrap (SURVEY.md §7
hard-part #4): the exact env the Indexed Job + headless Service render is fed
to two subprocesses; each must come up as one JAX process of a 2-process
cluster via workloads.multihost.initialize()."""

import json
import os
import socket
import subprocess
import sys

WORKER = r"""
import json, sys
from tpu_cluster.workloads import multihost
plan = multihost.initialize()
import jax
print(json.dumps({
    "plan": plan,
    "process_index": jax.process_index(),
    "process_count": jax.process_count(),
}))
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_jax_distributed_bootstrap(tmp_path):
    port = free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {
        **os.environ,
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "PALLAS_AXON_POOL_IPS": "",       # force local CPU backend
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        # what the rendered Indexed Job injects (render/jobs.py): the
        # headless-Service DNS names become localhost in this harness
        "TPU_WORKER_HOSTNAMES": "127.0.0.1,127.0.0.1",
        "TPU_COORDINATOR_PORT": str(port),
    }
    procs = []
    for idx in range(2):
        env = {**base_env, "JOB_COMPLETION_INDEX": str(idx)}
        env.pop("TPU_WORKER_ID", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = []
    for idx, proc in enumerate(procs):
        out, err = proc.communicate(timeout=120)
        assert proc.returncode == 0, f"worker {idx} failed:\n{err[-2000:]}"
        results.append(json.loads(out.splitlines()[-1]))

    assert {r["process_index"] for r in results} == {0, 1}
    for idx, r in enumerate(results):
        assert r["process_count"] == 2
        assert r["plan"]["multihost"] is True
        assert r["plan"]["num_processes"] == 2
        assert r["plan"]["process_id"] == idx
        assert r["plan"]["coordinator_address"] == f"127.0.0.1:{port}"
