#include "topology.h"

#include <algorithm>
#include <set>
#include <sstream>

namespace tpud {

namespace {

const std::vector<AcceleratorType>& Catalogue() {
  static const std::vector<AcceleratorType> kTypes = {
      {"v4-8", "v4", 4, 2, 2, 32, {4}, {{4, {2, 2}}}},
      {"v5e-1", "v5e", 1, 1, 1, 16, {1}, {{1, {1, 1}}}},
      {"v5e-4", "v5e", 4, 2, 2, 16, {1, 4}, {{1, {1, 1}}, {4, {2, 2}}}},
      {"v5e-8", "v5e", 8, 2, 4, 16, {1, 4, 8},
       {{1, {1, 1}}, {4, {2, 2}}, {8, {2, 4}}}},
      {"v5p-8", "v5p", 4, 2, 2, 95, {4}, {{4, {2, 2}}}},
      {"v6e-8", "v6e", 8, 2, 4, 32, {1, 4, 8},
       {{1, {1, 1}}, {4, {2, 2}}, {8, {2, 4}}}},
      // Multi-host slices: whole-host-group allocation only (aligned 8),
      // hosts tile the slice grid; mirrors tpu_cluster/topology.py.
      {"v5e-16", "v5e", 8, 2, 4, 16, {8}, {{8, {2, 4}}}, 2, 2, 1, 1},
      {"v5e-32", "v5e", 8, 2, 4, 16, {8}, {{8, {2, 4}}}, 4, 2, 2, 1},
      {"v6e-16", "v6e", 8, 2, 4, 32, {8}, {{8, {2, 4}}}, 2, 2, 1, 1},
      // v4/v5p hosts stack along the torus z axis: flat 2x2 chip groups
      // form 2x2xZ tori, TPU_HOST_BOUNDS "1,1,Z" (mirrors topology.py).
      {"v5p-16", "v5p", 4, 2, 2, 95, {4}, {{4, {2, 2}}}, 2, 1, 1, 2},
      {"v5p-32", "v5p", 4, 2, 2, 95, {4}, {{4, {2, 2}}}, 4, 1, 1, 4},
      {"v4-16", "v4", 4, 2, 2, 32, {4}, {{4, {2, 2}}}, 2, 1, 1, 2},
      // larger slices: v5e tiles x then y; v5p-64 is the first shape
      // tiling hosts along ALL THREE axes (2x2 groups -> the 4x4x2 torus)
      {"v5e-64", "v5e", 8, 2, 4, 16, {8}, {{8, {2, 4}}}, 8, 4, 2, 1},
      {"v6e-32", "v6e", 8, 2, 4, 32, {8}, {{8, {2, 4}}}, 4, 2, 2, 1},
      {"v5p-64", "v5p", 4, 2, 2, 95, {4}, {{4, {2, 2}}}, 8, 2, 2, 2},
  };
  return kTypes;
}

// Chip id -> coordinate, row-major: id = y * X + x (matches topology.py).
inline int CoordToId(const AcceleratorType& acc, int x, int y) {
  return y * acc.topo_x + x;
}

}  // namespace

const AcceleratorType* FindAccelerator(const std::string& name) {
  for (const auto& t : Catalogue())
    if (t.name == name) return &t;
  return nullptr;
}

std::vector<std::string> KnownAccelerators() {
  std::vector<std::string> out;
  for (const auto& t : Catalogue()) out.push_back(t.name);
  return out;
}

std::vector<std::vector<int>> AlignedSubsets(const AcceleratorType& acc,
                                             int size) {
  std::vector<std::vector<int>> out;
  const std::pair<int, int>* shape = nullptr;
  for (const auto& [sz, sh] : acc.sub_mesh_shapes)
    if (sz == size) shape = &sh;
  if (!shape) return out;
  std::set<std::vector<int>> uniq;
  // Both orientations of the rectangle.
  std::set<std::pair<int, int>> orients = {*shape,
                                           {shape->second, shape->first}};
  for (const auto& [w, h] : orients) {
    if (w > acc.topo_x || h > acc.topo_y) continue;
    for (int x0 = 0; x0 + w <= acc.topo_x; ++x0) {
      for (int y0 = 0; y0 + h <= acc.topo_y; ++y0) {
        std::vector<int> ids;
        for (int dx = 0; dx < w; ++dx)
          for (int dy = 0; dy < h; ++dy)
            ids.push_back(CoordToId(acc, x0 + dx, y0 + dy));
        std::sort(ids.begin(), ids.end());
        uniq.insert(std::move(ids));
      }
    }
  }
  out.assign(uniq.begin(), uniq.end());
  return out;
}

std::optional<std::vector<int>> PreferredAllocation(
    const AcceleratorType& acc, const std::vector<int>& available,
    const std::vector<int>& must_include, int size) {
  std::set<int> avail(available.begin(), available.end());
  std::set<int> must(must_include.begin(), must_include.end());
  if (static_cast<int>(must.size()) > size) return std::nullopt;
  for (int m : must)
    if (!avail.count(m)) return std::nullopt;
  for (const auto& subset : AlignedSubsets(acc, size)) {
    std::set<int> s(subset.begin(), subset.end());
    bool covers_must = std::includes(s.begin(), s.end(), must.begin(),
                                     must.end());
    bool within_avail =
        std::includes(avail.begin(), avail.end(), s.begin(), s.end());
    if (covers_must && within_avail) return subset;
  }
  return std::nullopt;
}

bool ValidateAllocation(const AcceleratorType& acc,
                        const std::vector<int>& device_ids,
                        std::string* reason) {
  std::vector<int> ids(device_ids);
  std::sort(ids.begin(), ids.end());
  int n = static_cast<int>(ids.size());
  auto join = [](const std::vector<int>& v) {
    std::ostringstream os;
    for (size_t i = 0; i < v.size(); ++i) os << (i ? "," : "") << v[i];
    return os.str();
  };
  // Rejections carry actionable hints: the allowed sizes WITH an example
  // aligned chip set each, so the pod event tells the user what to request
  // instead of only what failed (SURVEY.md §7 hard-part #2 UX).
  auto examples = [&] {
    std::ostringstream os;
    os << "; valid sizes (example chip set): ";
    for (size_t i = 0; i < acc.aligned_sizes.size(); ++i) {
      auto subsets = AlignedSubsets(acc, acc.aligned_sizes[i]);
      os << (i ? ", " : "") << acc.aligned_sizes[i];
      if (!subsets.empty()) os << " (" << join(subsets[0]) << ")";
    }
    return os.str();
  };
  if (std::find(acc.aligned_sizes.begin(), acc.aligned_sizes.end(), n) ==
      acc.aligned_sizes.end()) {
    std::ostringstream os;
    os << "request size " << n << " is not aligned for " << acc.name
       << examples();
    *reason = os.str();
    return false;
  }
  for (int id : ids) {
    if (id < 0 || id >= acc.chips_per_host) {
      *reason = "device ids out of range for " + acc.name;
      return false;
    }
  }
  if (std::set<int>(ids.begin(), ids.end()).size() != ids.size()) {
    *reason = "duplicate device ids in " + join(ids);
    return false;
  }
  auto subsets = AlignedSubsets(acc, n);
  if (std::find(subsets.begin(), subsets.end(), ids) != subsets.end()) {
    *reason = "aligned sub-mesh";
    return true;
  }
  std::ostringstream os;
  os << "device set " << join(ids) << " is not an ICI-contiguous sub-mesh of "
     << acc.name << " (" << acc.LabelTopology() << "); valid sets of size "
     << n << ": ";
  for (size_t i = 0; i < subsets.size(); ++i)
    os << (i ? " " : "") << "[" << join(subsets[i]) << "]";
  *reason = os.str();
  return false;
}

std::string GoldenJson() {
  std::ostringstream os;
  os << "{\"accelerators\": [";
  bool first_acc = true;
  for (const auto& acc : Catalogue()) {
    if (!first_acc) os << ", ";
    first_acc = false;
    os << "{\"name\": \"" << acc.name << "\", \"chips_per_host\": "
       << acc.chips_per_host << ", \"topology\": [" << acc.topo_x << ", "
       << acc.topo_y << "], \"aligned_sizes\": [";
    for (size_t i = 0; i < acc.aligned_sizes.size(); ++i)
      os << (i ? ", " : "") << acc.aligned_sizes[i];
    os << "], \"aligned_subsets\": {";
    for (size_t i = 0; i < acc.aligned_sizes.size(); ++i) {
      int sz = acc.aligned_sizes[i];
      os << (i ? ", " : "") << "\"" << sz << "\": [";
      auto subsets = AlignedSubsets(acc, sz);
      for (size_t j = 0; j < subsets.size(); ++j) {
        os << (j ? ", " : "") << "[";
        for (size_t k = 0; k < subsets[j].size(); ++k)
          os << (k ? ", " : "") << subsets[j][k];
        os << "]";
      }
      os << "]";
    }
    os << "}, \"validate_cases\": [";
    // Exhaustive combinations, same order as Python itertools.combinations.
    bool first_case = true;
    for (int n = 1; n <= acc.chips_per_host; ++n) {
      std::vector<int> combo(n);
      // Generate combinations in lexicographic order.
      for (int i = 0; i < n; ++i) combo[i] = i;
      while (true) {
        std::string reason;
        bool ok = ValidateAllocation(acc, combo, &reason);
        if (!first_case) os << ", ";
        first_case = false;
        os << "{\"ids\": [";
        for (int i = 0; i < n; ++i) os << (i ? ", " : "") << combo[i];
        os << "], \"ok\": " << (ok ? "true" : "false") << "}";
        // next combination
        int i = n - 1;
        while (i >= 0 && combo[i] == acc.chips_per_host - n + i) --i;
        if (i < 0) break;
        ++combo[i];
        for (int j = i + 1; j < n; ++j) combo[j] = combo[j - 1] + 1;
      }
    }
    os << "]}";
  }
  os << "]}";
  return os.str();
}

}  // namespace tpud
