// plugin_selftest — unit checks for the gang-reservation contract
// (reservation.h) plus a --check-reservations CLI mode so CI can replay a
// LIVE table produced by the Python admission loop through the C++
// enforcement (the "tpud selftest twin" of the e2e scenario).
//
// Protobuf-free on purpose: tpud itself needs protoc for the kubelet
// DevicePlugin proto, but the reservation contract must stay provable on
// hosts (and driver containers) that only have g++ — the same reasoning as
// the operator's g++-fallback targets in tests/conftest.py.

#include <stdio.h>
#include <string.h>

#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "reservation.h"
#include "topology.h"

static int g_failures = 0;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                    \
    }                                                                  \
  } while (0)

// The canonical reservation table the vector cases run against. Twin-read
// by tests/test_admission.py: the Python test greps this literal out of
// the selftest source, parses it with admission.parse_table, and replays
// kReservationVectors through admission.check_allocation — same verdicts,
// same matched gangs, or the twin pin fails.
static const char kReservationTableJson[] =
    "{\"version\": 1, \"gangs\": {"
    "\"train-a\": {\"accelerator\": \"v5e-16\", \"priority\": 10,"
    " \"hosts\": {\"node-a\": [0,1,2,3,4,5,6,7],"
    " \"node-b\": [0,1,2,3,4,5,6,7]}},"
    "\"probe\": {\"accelerator\": \"v5p-16\", \"priority\": 0,"
    " \"hosts\": {\"node-c\": [0,1,2,3]}},"
    "\"maint\": {\"accelerator\": \"v5e-8\", \"priority\": 1,"
    " \"hosts\": {\"node-m\": [0,1,2,3,4,5,6,7]}}},"
    " \"cordoned\": [\"node-m\", \"node-x\"]}";

struct ReservationCase {
  const char* host;
  const char* ids;  // comma-separated chip ids, "" = empty request
  bool ok;
  const char* gang;  // expected match on ok, "" otherwise
};

// Shared verdict vectors (grep-pinned by tests/test_admission.py; keep one
// initializer per line — the Python side parses them positionally).
static const ReservationCase kReservationVectors[] = {
    {"node-a", "0,1,2,3,4,5,6,7", true, "train-a"},
    {"node-b", "0,1,2,3,4,5,6,7", true, "train-a"},
    {"node-c", "0,1,2,3", true, "probe"},
    {"node-a", "0,1,2,3", false, ""},
    {"node-a", "4,5,6,7", false, ""},
    {"node-a", "0", false, ""},
    {"node-b", "0,1,2,3,4,5,6", false, ""},
    {"node-c", "0,1,2,3,4,5,6,7", false, ""},
    {"node-d", "0,1,2,3,4,5,6,7", false, ""},
    {"node-a", "0,0,1,2,3,4,5,6", false, ""},
    {"node-a", "", false, ""},
    {"node-m", "0,1,2,3,4,5,6,7", false, ""},
    {"node-x", "0,1,2,3,4,5,6,7", false, ""},
};

static std::vector<int> ParseIds(const char* csv) {
  std::vector<int> out;
  if (!*csv) return out;
  std::stringstream ss(csv);
  std::string tok;
  while (std::getline(ss, tok, ',')) out.push_back(atoi(tok.c_str()));
  return out;
}

static void TestContractConstants() {
  // Compiler-only half of the twin pin (the Python source-grep is the
  // other half): the wire contract is these exact strings.
  CHECK(strcmp(tpud::ReservationConfigMapName(), "tpu-gang-reservations")
        == 0);
  CHECK(strcmp(tpud::ReservationKey(), "reservations.json") == 0);
  CHECK(tpud::ReservationSchemaVersion() == 1);
  CHECK(strcmp(tpud::GangAnnotation(), "tpu-stack.dev/gang") == 0);
}

static void TestParse() {
  tpud::ReservationTable table;
  std::string err;
  CHECK(tpud::ParseReservations(kReservationTableJson, &table, &err));
  CHECK(err.empty());
  CHECK(table.version == 1);
  CHECK(table.gangs.size() == 3);
  CHECK(table.gangs.at("train-a").accelerator == "v5e-16");
  CHECK(table.gangs.at("train-a").priority == 10);
  CHECK(table.gangs.at("train-a").hosts.size() == 2);
  CHECK(table.gangs.at("probe").hosts.at("node-c") ==
        (std::vector<int>{0, 1, 2, 3}));
  // the cordoned-host list (ISSUE 18) rides the same document, sorted
  CHECK(table.cordoned ==
        (std::vector<std::string>{"node-m", "node-x"}));
  // chip ids are normalised sorted regardless of published order
  tpud::ReservationTable scrambled;
  CHECK(tpud::ParseReservations(
      "{\"version\": 1, \"gangs\": {\"g\": {\"accelerator\": \"v4-8\","
      " \"hosts\": {\"h\": [3,1,0,2]}}}}", &scrambled, &err));
  CHECK(scrambled.gangs.at("g").hosts.at("h") ==
        (std::vector<int>{0, 1, 2, 3}));
  // empty table (nothing admitted) parses fine
  tpud::ReservationTable empty;
  CHECK(tpud::ParseReservations("{\"version\": 1, \"gangs\": {}}", &empty,
                                &err));
  CHECK(empty.gangs.empty());
  CHECK(empty.cordoned.empty());
  CHECK(tpud::ParseReservations("{\"version\": 1}", &empty, &err));
  // the cordoned list survives a gangs-absent document (it is parsed
  // BEFORE the empty-table early return) and normalises sorted
  tpud::ReservationTable cordons;
  CHECK(tpud::ParseReservations(
      "{\"version\": 1, \"cordoned\": [\"h2\", \"h1\"]}", &cordons, &err));
  CHECK(cordons.gangs.empty());
  CHECK(cordons.cordoned == (std::vector<std::string>{"h1", "h2"}));
}

static void TestParseRejects() {
  tpud::ReservationTable table;
  std::string err;
  CHECK(!tpud::ParseReservations("not json", &table, &err));
  CHECK(!err.empty());
  CHECK(!tpud::ParseReservations("{\"version\": 2, \"gangs\": {}}", &table,
                                 &err));
  CHECK(err.find("version") != std::string::npos);
  CHECK(!tpud::ParseReservations("{\"gangs\": {}}", &table, &err));
  CHECK(!tpud::ParseReservations(
      "{\"version\": 1, \"gangs\": {\"g\": {\"hosts\": {\"h\": [\"x\"]}}}}",
      &table, &err));
  // a failed parse leaves the table EMPTY (fail closed at Allocate, never
  // half-loaded)
  CHECK(table.gangs.empty() && table.version == 0);
  // a malformed cordoned list fails the WHOLE table closed, same unit
  CHECK(!tpud::ParseReservations(
      "{\"version\": 1, \"gangs\": {}, \"cordoned\": [1]}", &table, &err));
  CHECK(err.find("cordoned") != std::string::npos);
  CHECK(table.gangs.empty() && table.cordoned.empty());
}

static void TestCheckAllocationVectors() {
  tpud::ReservationTable table;
  std::string err;
  CHECK(tpud::ParseReservations(kReservationTableJson, &table, &err));
  for (const auto& c : kReservationVectors) {
    std::string gang, reason;
    bool ok = tpud::CheckAllocation(table, c.host, ParseIds(c.ids), &gang,
                                    &reason);
    if (ok != c.ok || gang != c.gang) {
      fprintf(stderr, "FAIL reservation vector host=%s ids=[%s]: "
              "got ok=%d gang='%s' (%s), want ok=%d gang='%s'\n",
              c.host, c.ids, ok ? 1 : 0, gang.c_str(), reason.c_str(),
              c.ok ? 1 : 0, c.gang);
      ++g_failures;
    }
  }
  // the partial-seat refusal NAMES the fraction — that string reaches the
  // pod event, it must say what actually went wrong
  std::string gang, reason;
  CHECK(!tpud::CheckAllocation(table, "node-a", {0, 1, 2, 3}, &gang,
                               &reason));
  CHECK(reason.find("partial") != std::string::npos);
  CHECK(reason.find("4 of 8") != std::string::npos);
  CHECK(!tpud::CheckAllocation(table, "node-z", {0}, &gang, &reason));
  CHECK(reason.find("no admitted gang") != std::string::npos);
  // cordon beats reservation: node-m still has an admitted gang in the
  // table, but the maintenance cordon refuses the seat by name
  CHECK(!tpud::CheckAllocation(table, "node-m", {0, 1, 2, 3, 4, 5, 6, 7},
                               &gang, &reason));
  CHECK(reason.find("cordoned for maintenance") != std::string::npos);
}

static void TestTopologyStillAgrees() {
  // Sanity coupling with the catalogue: every vector's accepted set is a
  // whole host group of its accelerator (gang reservations are whole-host
  // by construction in the admission loop).
  const tpud::AcceleratorType* v5e16 = tpud::FindAccelerator("v5e-16");
  CHECK(v5e16 != nullptr && v5e16->chips_per_host == 8);
  const tpud::AcceleratorType* v5p16 = tpud::FindAccelerator("v5p-16");
  CHECK(v5p16 != nullptr && v5p16->chips_per_host == 4);
  std::string reason;
  CHECK(tpud::ValidateAllocation(*v5e16, {0, 1, 2, 3, 4, 5, 6, 7},
                                 &reason));
  CHECK(tpud::ValidateAllocation(*v5p16, {0, 1, 2, 3}, &reason));
}

// --check-reservations FILE --host H --devices 0,1,... : replay a live
// table (e.g. the ConfigMap payload the admission loop just published)
// through the C++ enforcement. Exit 0 admitted (gang on stdout), 3 denied
// (reason on stderr), 2 usage/parse error.
static int CheckReservationsCli(int argc, char** argv) {
  std::string file, host, devices;
  for (int i = 2; i + 1 < argc; i += 2) {
    if (strcmp(argv[i], "--host") == 0) host = argv[i + 1];
    else if (strcmp(argv[i], "--devices") == 0) devices = argv[i + 1];
    else { fprintf(stderr, "unknown flag %s\n", argv[i]); return 2; }
  }
  file = argv[1] + strlen("--check-reservations=");
  std::ifstream in(file);
  if (!in) {
    fprintf(stderr, "cannot read %s\n", file.c_str());
    return 2;
  }
  std::stringstream buf;
  buf << in.rdbuf();
  tpud::ReservationTable table;
  std::string err;
  if (!tpud::ParseReservations(buf.str(), &table, &err)) {
    fprintf(stderr, "%s\n", err.c_str());
    return 2;
  }
  std::string gang, reason;
  if (tpud::CheckAllocation(table, host, ParseIds(devices.c_str()), &gang,
                            &reason)) {
    printf("%s\n", gang.c_str());
    return 0;
  }
  fprintf(stderr, "%s\n", reason.c_str());
  return 3;
}

int main(int argc, char** argv) {
  if (argc > 1 &&
      strncmp(argv[1], "--check-reservations=",
              strlen("--check-reservations=")) == 0) {
    return CheckReservationsCli(argc, argv);
  }
  TestContractConstants();
  TestParse();
  TestParseRejects();
  TestCheckAllocationVectors();
  TestTopologyStillAgrees();
  if (g_failures) {
    fprintf(stderr, "plugin_selftest: %d failure(s)\n", g_failures);
    return 1;
  }
  printf("plugin_selftest: all checks passed\n");
  return 0;
}
