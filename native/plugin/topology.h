// TPU per-host topology model + aligned-allocation policy (C++).
//
// Mirror of tpu_cluster/topology.py — the two implementations are pinned to
// the same golden vectors (tests/data/topology_golden.json via
// tests/test_native.py). Policy rationale lives in the Python docstrings.
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

namespace tpud {

struct AcceleratorType {
  std::string name;        // "v5e-8"
  std::string generation;  // "v5e"
  int chips_per_host;
  int topo_x, topo_y;      // per-host chip grid
  int hbm_gib_per_chip;
  std::vector<int> aligned_sizes;
  // size -> sub-mesh rectangle (w, h)
  std::vector<std::pair<int, std::pair<int, int>>> sub_mesh_shapes;
  // Multi-host slices: hosts tiling the slice grid (1,1,1 = single host).
  // Drives the TPU_HOST_BOUNDS env in Allocate (tpud.cc); per-host
  // ListAndWatch/Allocate semantics are unchanged.
  int num_hosts = 1;
  int hosts_x = 1, hosts_y = 1, hosts_z = 1;

  // Slice chip grid (hosts x per-host grid) — matches Python
  // label_topology(); equals the per-host grid on 1-host types. v4/v5p
  // slices tile a 3D torus: their labels carry the z extent (= hosts_z,
  // per-host grids are always flat), the GKE convention for those
  // generations.
  std::string LabelTopology() const {
    std::string label = std::to_string(topo_x * hosts_x) + "x" +
                        std::to_string(topo_y * hosts_y);
    if (generation == "v4" || generation == "v5p")
      label += "x" + std::to_string(hosts_z);
    return label;
  }
  std::string HostBounds() const {
    return std::to_string(hosts_x) + "," + std::to_string(hosts_y) + "," +
           std::to_string(hosts_z);
  }
};

// nullptr when unknown.
const AcceleratorType* FindAccelerator(const std::string& name);
std::vector<std::string> KnownAccelerators();

// All chip-id subsets of `size` forming a valid ICI sub-mesh; sorted, each
// subset sorted (deterministic; matches Python aligned_subsets()).
std::vector<std::vector<int>> AlignedSubsets(const AcceleratorType& acc,
                                             int size);

// GetPreferredAllocation policy: aligned sub-mesh covering must_include from
// available, lowest chip ids first. nullopt when impossible.
std::optional<std::vector<int>> PreferredAllocation(
    const AcceleratorType& acc, const std::vector<int>& available,
    const std::vector<int>& must_include, int size);

// Allocate() admission check. Returns true when device_ids is an aligned
// sub-mesh; fills *reason either way.
bool ValidateAllocation(const AcceleratorType& acc,
                        const std::vector<int>& device_ids,
                        std::string* reason);

// Emits the same JSON structure as tests/data/topology_golden.json so the
// Python test can diff the two implementations byte-for-byte (modulo
// formatting).
std::string GoldenJson();

}  // namespace tpud
