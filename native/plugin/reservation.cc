// Gang-reservation table parsing + Allocate enforcement (see reservation.h).

#include "reservation.h"

#include <algorithm>
#include <set>

#include "../operator/minijson.h"

namespace tpud {

// Contract constants — twins of tpu_cluster/admission.py
// (RESERVATION_CONFIGMAP / RESERVATION_KEY / RESERVATION_SCHEMA_VERSION /
// GANG_ANNOTATION). tests/test_admission.py greps these literals; a rename
// here without the Python twin fails that pin before it fails a cluster.
const char* ReservationConfigMapName() { return "tpu-gang-reservations"; }
const char* ReservationKey() { return "reservations.json"; }
int ReservationSchemaVersion() { return 1; }
const char* GangAnnotation() { return "tpu-stack.dev/gang"; }

bool ParseReservations(const std::string& json_text, ReservationTable* table,
                       std::string* err) {
  // fail closed as a unit: any error leaves *table EMPTY, never
  // half-loaded (Allocate enforcement keys on the whole table)
  *table = ReservationTable();
  ReservationTable out;
  std::string parse_err;
  minijson::ValuePtr doc = minijson::Parse(json_text, &parse_err);
  if (!doc || !doc->is_object()) {
    *err = "reservations: not a JSON object" +
           (parse_err.empty() ? "" : " (" + parse_err + ")");
    return false;
  }
  int version = static_cast<int>(doc->PathNumber("version", -1));
  if (version != ReservationSchemaVersion()) {
    *err = "reservations: unsupported schema version " +
           std::to_string(version) + " (want " +
           std::to_string(ReservationSchemaVersion()) + ")";
    return false;
  }
  out.version = version;
  // "cordoned" (ISSUE 18): optional string array of hosts under
  // maintenance; parsed before gangs so an empty-gangs table still
  // carries its cordon set. Fails closed as a unit like everything else.
  minijson::ValuePtr cordoned = doc->Get("cordoned");
  if (cordoned) {
    if (!cordoned->is_array()) {
      *err = "reservations: 'cordoned' is not an array";
      return false;
    }
    for (const auto& v : cordoned->elements()) {
      if (!v || !v->is_string()) {
        *err = "reservations: 'cordoned' has a non-string host";
        return false;
      }
      out.cordoned.push_back(v->as_string());
    }
    std::sort(out.cordoned.begin(), out.cordoned.end());
  }
  minijson::ValuePtr gangs = doc->Get("gangs");
  if (!gangs) {  // empty table: nothing admitted
    *table = std::move(out);
    return true;
  }
  if (!gangs->is_object()) {
    *err = "reservations: 'gangs' is not an object";
    return false;
  }
  for (const auto& item : gangs->items()) {
    GangReservation res;
    res.gang = item.first;
    if (!item.second || !item.second->is_object()) {
      *err = "reservations: gang '" + item.first + "' is not an object";
      return false;
    }
    res.accelerator = item.second->PathString("accelerator");
    res.priority = static_cast<int>(item.second->PathNumber("priority", 0));
    minijson::ValuePtr hosts = item.second->Get("hosts");
    if (hosts && hosts->is_object()) {
      for (const auto& h : hosts->items()) {
        if (!h.second || !h.second->is_array()) {
          *err = "reservations: gang '" + item.first + "' host '" +
                 h.first + "' chip list is not an array";
          return false;
        }
        std::vector<int> ids;
        for (const auto& v : h.second->elements()) {
          if (!v || !v->is_number()) {
            *err = "reservations: gang '" + item.first +
                   "' has a non-numeric chip id";
            return false;
          }
          ids.push_back(static_cast<int>(v->as_number()));
        }
        std::sort(ids.begin(), ids.end());
        res.hosts[h.first] = std::move(ids);
      }
    }
    out.gangs[res.gang] = std::move(res);
  }
  *table = std::move(out);
  return true;
}

bool CheckAllocation(const ReservationTable& table, const std::string& host,
                     const std::vector<int>& device_ids, std::string* gang,
                     std::string* reason) {
  gang->clear();
  std::set<int> want(device_ids.begin(), device_ids.end());
  if (want.size() != device_ids.size()) {
    *reason = "duplicate device ids in allocation request";
    return false;
  }
  // Maintenance cordon beats any reservation still naming the host
  // (ISSUE 18): during the drain race window the kubelet must not seat
  // a gang the controller is about to drain. Wording twin of the
  // Python check_allocation.
  if (std::binary_search(table.cordoned.begin(), table.cordoned.end(),
                         host)) {
    *reason = "host '" + host + "' is cordoned for maintenance; gangs "
              "are not seated on a cordoned host";
    return false;
  }
  bool host_reserved = false;
  for (const auto& entry : table.gangs) {
    const GangReservation& res = entry.second;
    auto it = res.hosts.find(host);
    if (it == res.hosts.end()) continue;
    host_reserved = true;
    std::set<int> reserved(it->second.begin(), it->second.end());
    if (reserved == want) {
      *gang = res.gang;
      *reason = "admitted gang '" + res.gang + "'";
      return true;
    }
    if (!want.empty() &&
        std::includes(reserved.begin(), reserved.end(), want.begin(),
                      want.end())) {
      // The failure this layer exists for: seating a FRACTION of an
      // admitted gang's host group. Name it so the pod event says
      // "partial", not just "denied".
      *reason = "partial allocation of gang '" + res.gang + "' on host '" +
                host + "': requested " + std::to_string(want.size()) +
                " of " + std::to_string(reserved.size()) +
                " reserved chip(s); gangs are seated whole or not at all";
      return false;
    }
  }
  if (host_reserved) {
    *reason = "device set does not match any admitted gang reservation on "
              "host '" + host + "'";
  } else {
    *reason = "no admitted gang reservation covers host '" + host +
              "'; the admission loop has not granted this job chips";
  }
  return false;
}

}  // namespace tpud
