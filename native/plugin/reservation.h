// Gang-reservation table: the Python admission loop's published contract,
// enforced at the device plugin's Allocate.
//
// The admission controller (tpu_cluster/admission.py) arbitrates contending
// multi-host gangs all-or-nothing and publishes the resulting reservation
// table as a ConfigMap (name/key pinned below). tpud loads the table (the
// ConfigMap is projected to a file, --reservations=PATH) and rejects any
// Allocate whose device set is not EXACTLY one admitted gang's per-host
// reservation — the kubelet can never seat a partial gang. Contract twin of
// the Python constants/checker in tpu_cluster/admission.py, pinned by
// native/plugin/selftest.cc (compiler-only) and a source-grep in
// tests/test_admission.py (the RetryableStatus pattern).
#pragma once

#include <map>
#include <string>
#include <vector>

namespace tpud {

// ---- contract constants (twin of tpu_cluster/admission.py; keep literal
// initializers greppable — tests regex them out of reservation.cc).
const char* ReservationConfigMapName();   // ConfigMap metadata.name
const char* ReservationKey();             // data key holding the JSON table
int ReservationSchemaVersion();           // "version" field the parser accepts
const char* GangAnnotation();             // workload annotation naming a gang

struct GangReservation {
  std::string gang;
  std::string accelerator;
  int priority = 0;
  // host -> reserved chip ids (sorted)
  std::map<std::string, std::vector<int>> hosts;
};

struct ReservationTable {
  int version = 0;
  // gang name -> reservation, insertion-ordered by name (std::map)
  std::map<std::string, GangReservation> gangs;
  // hosts cordoned for maintenance (ISSUE 18), sorted: an ADDITIVE
  // schema-v1 field — absent parses as empty. CheckAllocation refuses
  // any seat on a cordoned host even while a reservation still names it
  // (the drain race window between cordon and the admission pass).
  std::vector<std::string> cordoned;
};

// Parse the reservations.json document. False on malformed JSON, a wrong
// schema version, or non-integer chip ids; *err names the reason.
bool ParseReservations(const std::string& json_text, ReservationTable* table,
                       std::string* err);

// The Allocate() enforcement: true iff `device_ids` is EXACTLY the chip set
// some admitted gang reserves on `host` (order-insensitive, duplicates
// rejected). On success *gang names the matching gang; on failure *reason
// says why — a proper subset of a reservation is called out as a PARTIAL
// gang seat (the failure mode this whole layer exists to prevent).
bool CheckAllocation(const ReservationTable& table, const std::string& host,
                     const std::vector<int>& device_ids, std::string* gang,
                     std::string* reason);

}  // namespace tpud
