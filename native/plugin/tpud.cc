// tpud — TPU device plugin daemon (the stack's centerpiece).
//
// Native replacement for the reference's nvidia-device-plugin-daemonset
// (reference README.md:106,211; SURVEY.md §2.2): registers with the kubelet
// over the DevicePlugin v1beta1 gRPC API, ListAndWatches chips discovered
// from /dev/accel* (or synthesised in --fake-devices mode, the clusterless
// test story of SURVEY.md §4), advertises the `google.com/tpu` extended
// resource, answers topology-aligned GetPreferredAllocation, and returns
// device nodes + env + libtpu mount from Allocate — which on TPU also covers
// the capability the GPU stack needs nvidia-container-toolkit for
// (reference README.md:105,210; docs/DELTAS.md).
//
// Design: single-threaded poll loop (grpcmin::Server::RunOnce) + periodic
// device rescans and kubelet (re-)registration. Kubelet restarts are detected
// by watching the registration socket inode; the plugin re-registers, which
// is the subtle lifecycle requirement SURVEY.md §7 ranks hard-part #1.

#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "deviceplugin.pb.h"
#include "../common/devenum.h"
#include "../grpcmin/grpc.h"
#include "reservation.h"
#include "topology.h"

namespace {

volatile sig_atomic_t g_stop = 0;
void HandleSignal(int) { g_stop = 1; }

struct Options {
  std::string resource = "google.com/tpu";
  std::string accelerator = "v5e-8";
  std::string device_glob = "/dev/accel*";
  std::string libtpu_path = "/var/lib/tpu/libtpu.so";
  std::string kubelet_dir = "/var/lib/kubelet/device-plugins";
  std::string endpoint = "tpud.sock";
  std::string devfs_root;          // re-roots device_glob (tests)
  // Gang admission (ISSUE 10): path of the reservation table the Python
  // admission loop publishes (the tpu-gang-reservations ConfigMap,
  // projected to a file). Empty = enforcement off, Allocate behaves
  // exactly as before — the no-gangs hot path is byte-identical.
  std::string reservations_path;
  std::string node_name;           // this host's Node name (reservation key)
  int fake_devices = -1;           // >=0: synthesise N chips, no device files
  bool do_register = true;
  bool print_topology_golden = false;
  int rescan_interval_s = 3;
};

struct ChipDevice {
  int index;
  std::string path;
  bool healthy;
  int numa_node = -1;
  bool vfio = false;  // classified once at discovery
};

std::string DeviceId(int index) { return "tpu-" + std::to_string(index); }

int ParseIndexFromId(const std::string& id) {
  if (id.rfind("tpu-", 0) != 0) return -1;
  return atoi(id.c_str() + 4);
}

int ReadNumaNode(const std::string& dev_path) {
  // /dev/accelN -> /sys/class/accel/accelN/device/numa_node
  const char* base = strrchr(dev_path.c_str(), '/');
  if (!base) return -1;
  std::string sysfs = "/sys/class/accel/" + std::string(base + 1) +
                      "/device/numa_node";
  FILE* f = fopen(sysfs.c_str(), "r");
  if (!f) return -1;
  int node = -1;
  if (fscanf(f, "%d", &node) != 1) node = -1;
  fclose(f);
  return node;
}

std::vector<ChipDevice> DiscoverDevices(const Options& opt) {
  std::vector<ChipDevice> out;
  if (opt.fake_devices >= 0) {
    for (int i = 0; i < opt.fake_devices; ++i)
      out.push_back({i, "/dev/accel" + std::to_string(i), true, -1});
    return out;
  }
  // Shared enumeration (native/common/devenum.cc): glob, basename parse,
  // sorted by index — same nodes every native daemon counts.
  for (const auto& node : devenum::Enumerate(opt.device_glob, opt.devfs_root))
    out.push_back({node.index, node.path,
                   access(node.path.c_str(), F_OK) == 0,
                   ReadNumaNode(node.path)});
  // VFIO group nodes carry host-global IOMMU group numbers (e.g.
  // /dev/vfio/45..48), which are NOT chip topology coordinates. Re-rank
  // them densely 0..N-1 (sorted group order) so device ids, sub-mesh math,
  // and TPU_VISIBLE_DEVICES stay chip-indexed; the host path keeps the
  // group identity for the container mount.
  for (size_t i = 0; i < out.size(); ++i) {
    if (out[i].path.find("/vfio/") != std::string::npos) {
      out[i].vfio = true;
      out[i].index = static_cast<int>(i);
    }
  }
  return out;
}

class Plugin {
 public:
  Plugin(const Options& opt, const tpud::AcceleratorType& acc)
      : opt_(opt), acc_(acc) {}

  bool Init() {
    socket_path_ = opt_.kubelet_dir + "/" + opt_.endpoint;
    if (!server_.Listen(socket_path_)) {
      fprintf(stderr, "tpud: cannot listen on %s: %s\n", socket_path_.c_str(),
              strerror(errno));
      return false;
    }
    devices_ = DiscoverDevices(opt_);
    fprintf(stderr, "tpud: serving %s on %s (%zu chips, accelerator=%s)\n",
            opt_.resource.c_str(), socket_path_.c_str(), devices_.size(),
            acc_.name.c_str());
    if (!opt_.reservations_path.empty()) {
      ReloadReservations();
      fprintf(stderr,
              "tpud: gang admission armed (reservations=%s node=%s): "
              "Allocate only seats whole admitted gangs\n",
              opt_.reservations_path.c_str(), opt_.node_name.c_str());
    }
    RegisterMethods();
    return true;
  }

  void Run() {
    time_t last_rescan = 0, last_reg_check = 0;
    while (!g_stop) {
      server_.RunOnce(200);
      time_t now = time(nullptr);
      if (now - last_rescan >= opt_.rescan_interval_s) {
        last_rescan = now;
        Rescan();
        if (!opt_.reservations_path.empty()) ReloadReservations();
      }
      if (now - last_reg_check >= 2) {
        last_reg_check = now;
        CheckOwnSocket();
        if (opt_.do_register) MaybeRegister();
      }
    }
    fprintf(stderr, "tpud: shutting down\n");
    server_.Shutdown();
  }

 private:
  // ---------------------------------------------------------- services

  void RegisterMethods() {
    using grpcmin::Status;
    using grpcmin::StatusCode;

    server_.AddUnary(
        "/v1beta1.DevicePlugin/GetDevicePluginOptions",
        [](const std::string&, std::string* resp) {
          v1beta1::DevicePluginOptions opts;
          opts.set_get_preferred_allocation_available(true);
          opts.SerializeToString(resp);
          return Status::Ok();
        });

    server_.AddServerStreaming(
        "/v1beta1.DevicePlugin/ListAndWatch",
        [this](const std::string&, grpcmin::ServerStream* stream) {
          stream->on_closed = [this, stream]() { watchers_.erase(stream); };
          watchers_.insert(stream);
          stream->Send(SerializeDeviceList());
        });

    server_.AddUnary(
        "/v1beta1.DevicePlugin/GetPreferredAllocation",
        [this](const std::string& req_bytes, std::string* resp) {
          v1beta1::PreferredAllocationRequest req;
          if (!req.ParseFromString(req_bytes))
            return Status{StatusCode::kInvalidArgument, "bad request proto"};
          v1beta1::PreferredAllocationResponse resp_pb;
          for (const auto& creq : req.container_requests()) {
            auto* cresp = resp_pb.add_container_responses();
            std::vector<int> avail, must;
            for (const auto& id : creq.available_deviceids())
              avail.push_back(ParseIndexFromId(id));
            for (const auto& id : creq.must_include_deviceids())
              must.push_back(ParseIndexFromId(id));
            auto pick = tpud::PreferredAllocation(acc_, avail, must,
                                                  creq.allocation_size());
            if (pick) {
              for (int idx : *pick) cresp->add_deviceids(DeviceId(idx));
            }
            // Empty response lets kubelet fall back to its own pick, which
            // Allocate() will then admission-check.
          }
          resp_pb.SerializeToString(resp);
          return Status::Ok();
        });

    server_.AddUnary(
        "/v1beta1.DevicePlugin/Allocate",
        [this](const std::string& req_bytes, std::string* resp) {
          v1beta1::AllocateRequest req;
          if (!req.ParseFromString(req_bytes))
            return Status{StatusCode::kInvalidArgument, "bad request proto"};
          v1beta1::AllocateResponse resp_pb;
          for (const auto& creq : req.container_requests()) {
            std::vector<int> ids;
            for (const auto& id : creq.devicesids())
              ids.push_back(ParseIndexFromId(id));
            std::string reason;
            if (!tpud::ValidateAllocation(acc_, ids, &reason)) {
              // Surfaces in the pod event — the admission story for
              // unaligned requests (SURVEY.md §7 hard-part #2).
              return Status{StatusCode::kInvalidArgument, reason};
            }
            // Gang enforcement (ISSUE 10): with a reservation table armed,
            // the device set must be EXACTLY one admitted gang's host
            // group — the kubelet cannot seat a fraction of a gang, and a
            // job the admission loop never admitted gets nothing. Fails
            // CLOSED on a missing/unparseable table (chips held back
            // beat chips double-booked).
            std::string gang;
            if (!opt_.reservations_path.empty()) {
              if (!res_ok_) {
                return Status{StatusCode::kUnavailable,
                              "gang reservations unavailable: " + res_err_};
              }
              if (!tpud::CheckAllocation(reservations_, opt_.node_name, ids,
                                         &gang, &reason)) {
                return Status{StatusCode::kPermissionDenied, reason};
              }
            }
            FillContainerResponse(ids, gang,
                                  resp_pb.add_container_responses());
          }
          resp_pb.SerializeToString(resp);
          return Status::Ok();
        });

    server_.AddUnary("/v1beta1.DevicePlugin/PreStartContainer",
                     [](const std::string&, std::string* resp) {
                       v1beta1::PreStartContainerResponse r;
                       r.SerializeToString(resp);
                       return Status::Ok();
                     });
  }

  void FillContainerResponse(const std::vector<int>& ids,
                             const std::string& gang,
                             v1beta1::ContainerAllocateResponse* cresp) {
    std::vector<int> sorted_ids(ids);
    std::sort(sorted_ids.begin(), sorted_ids.end());
    std::string visible;
    for (size_t i = 0; i < sorted_ids.size(); ++i)
      visible += (i ? "," : "") + std::to_string(sorted_ids[i]);

    // Device nodes. accel devices keep the canonical /dev/accelN container
    // layout regardless of host devfs rerooting; VFIO-passthrough devices
    // must keep their /dev/vfio/N identity (libtpu opens them by that
    // name) plus the /dev/vfio/vfio container node, added once.
    //
    // Fake mode allocates env-only: the synthesized /dev/accelN paths
    // don't exist on the host, and a DeviceSpec referencing a missing
    // node makes runc fail container creation — which would break the
    // very clusterless e2e (kind, SURVEY.md §4 point 3) fake mode exists
    // for. Real-device and devfs-rerooted paths keep full DeviceSpecs.
    bool vfio_ctl_added = false;
    if (opt_.fake_devices < 0) {
      for (int idx : sorted_ids) {
        const ChipDevice* dev = FindDevice(idx);
        auto* spec = cresp->add_devices();
        if (dev && dev->vfio) {
          // keep the IOMMU group identity (basename), not the chip index —
          // libtpu opens the group node by its real name
          std::string group = dev->path.substr(dev->path.rfind('/') + 1);
          spec->set_container_path("/dev/vfio/" + group);
          spec->set_host_path(dev->path);
          if (!vfio_ctl_added) {
            vfio_ctl_added = true;
            auto* ctl = cresp->add_devices();
            ctl->set_container_path("/dev/vfio/vfio");
            // honour devfs rerooting (tests): the control node sits beside
            // the group nodes on the host
            std::string dir = dev->path.substr(0, dev->path.rfind('/'));
            ctl->set_host_path(dir + "/vfio");
            ctl->set_permissions("rw");
          }
        } else {
          spec->set_container_path("/dev/accel" + std::to_string(idx));
          spec->set_host_path(dev ? dev->path
                                  : "/dev/accel" + std::to_string(idx));
        }
        spec->set_permissions("rw");
      }
    }

    // Sub-mesh bounds of the allocated chip set (bounding box of coords).
    int min_x = acc_.topo_x, max_x = -1, min_y = acc_.topo_y, max_y = -1;
    for (int idx : sorted_ids) {
      int x = idx % acc_.topo_x, y = idx / acc_.topo_x;
      min_x = std::min(min_x, x); max_x = std::max(max_x, x);
      min_y = std::min(min_y, y); max_y = std::max(max_y, y);
    }
    int w = max_x - min_x + 1, h = max_y - min_y + 1;

    // The env contract consumed by libtpu/JAX in the workload container —
    // the TPU delta replacing the container-toolkit hook (docs/DELTAS.md).
    auto& envs = *cresp->mutable_envs();
    envs["TPU_VISIBLE_DEVICES"] = visible;
    envs["TPU_CHIPS_PER_HOST_BOUNDS"] =
        std::to_string(w) + "," + std::to_string(h) + ",1";
    // Host tiling of the slice from the accelerator catalogue — "1,1,1" on
    // single-host types, "2,1,1" on v5e-16 etc. Worker identity within the
    // slice (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES) is Job-level, injected
    // by the Indexed-Job manifest (render/jobs.py), not per-Allocate.
    envs["TPU_HOST_BOUNDS"] = acc_.HostBounds();
    envs["TPU_SKIP_MDS_QUERY"] = "true";
    envs["TPU_ACCELERATOR_TYPE"] = acc_.name;
    envs["TPU_DEVICE_COUNT"] = std::to_string(sorted_ids.size());

    if (!opt_.libtpu_path.empty()) {
      std::string dir = opt_.libtpu_path.substr(
          0, opt_.libtpu_path.find_last_of('/'));
      auto* m = cresp->add_mounts();
      m->set_container_path(dir);
      m->set_host_path(dir);
      m->set_read_only(true);
      envs["TPU_LIBRARY_PATH"] = opt_.libtpu_path;
    }
    if (!gang.empty()) {
      // the seated gang's identity, visible to the workload (JAX-side
      // diagnostics) and on the container (kubectl describe)
      envs["TPU_GANG_NAME"] = gang;
      (*cresp->mutable_annotations())[tpud::GangAnnotation()] = gang;
    }
    (*cresp->mutable_annotations())["tpu.native/allocation"] = visible;
  }

  // Load/refresh the admission loop's reservation table (mtime-gated; a
  // vanished or unparseable file flips res_ok_ false so Allocate fails
  // closed instead of enforcing a stale half-table).
  void ReloadReservations() {
    struct stat st;
    if (stat(opt_.reservations_path.c_str(), &st) != 0) {
      if (res_ok_ || res_err_.empty()) {
        fprintf(stderr, "tpud: reservations file %s missing; Allocate "
                "fails closed until it returns\n",
                opt_.reservations_path.c_str());
      }
      res_ok_ = false;
      res_err_ = "reservations file missing: " + opt_.reservations_path;
      res_mtim_ = {0, 0};
      res_size_ = -1;
      return;
    }
    // nanosecond mtime + size: a sub-second admission loop can rewrite
    // the table twice within one st_mtime second — whole-second
    // comparison would enforce the stale table indefinitely
    if (res_ok_ && st.st_mtim.tv_sec == res_mtim_.tv_sec &&
        st.st_mtim.tv_nsec == res_mtim_.tv_nsec &&
        st.st_size == res_size_) {
      return;  // unchanged
    }
    FILE* f = fopen(opt_.reservations_path.c_str(), "r");
    if (!f) {
      res_ok_ = false;
      res_err_ = "cannot open reservations file";
      return;
    }
    std::string text;
    char buf[4096];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), f)) > 0) text.append(buf, n);
    fclose(f);
    std::string err;
    tpud::ReservationTable table;
    if (!tpud::ParseReservations(text, &table, &err)) {
      fprintf(stderr, "tpud: %s; Allocate fails closed\n", err.c_str());
      res_ok_ = false;
      res_err_ = err;
      return;
    }
    reservations_ = std::move(table);
    res_ok_ = true;
    res_err_.clear();
    res_mtim_ = st.st_mtim;
    res_size_ = st.st_size;
    fprintf(stderr, "tpud: loaded %zu gang reservation(s)\n",
            reservations_.gangs.size());
  }

  // ---------------------------------------------------------- devices

  const ChipDevice* FindDevice(int index) const {
    for (const auto& d : devices_)
      if (d.index == index) return &d;
    return nullptr;
  }

  std::string SerializeDeviceList() const {
    v1beta1::ListAndWatchResponse resp;
    for (const auto& d : devices_) {
      auto* dev = resp.add_devices();
      dev->set_id(DeviceId(d.index));
      dev->set_health(d.healthy ? "Healthy" : "Unhealthy");
      if (d.numa_node >= 0)
        dev->mutable_topology()->add_nodes()->set_id(d.numa_node);
    }
    std::string out;
    resp.SerializeToString(&out);
    return out;
  }

  void Rescan() {
    auto found = DiscoverDevices(opt_);
    bool changed = found.size() != devices_.size();
    if (!changed) {
      for (size_t i = 0; i < found.size(); ++i) {
        // Path matters: VFIO re-ranking keeps indices dense 0..N-1, so an
        // IOMMU-group renumbering is visible only through the host path.
        if (found[i].index != devices_[i].index ||
            found[i].healthy != devices_[i].healthy ||
            found[i].path != devices_[i].path) {
          changed = true;
          break;
        }
      }
    }
    if (changed) {
      fprintf(stderr, "tpud: device set changed (%zu -> %zu chips)\n",
              devices_.size(), found.size());
      devices_ = std::move(found);
      std::string update = SerializeDeviceList();
      for (auto* w : std::set<grpcmin::ServerStream*>(watchers_))
        w->Send(update);
    }
  }

  // ---------------------------------------------------------- registration

  // A restarting kubelet wipes the device-plugins dir, deleting our endpoint
  // socket — the canonical re-register signal. (We cannot rely on the
  // kubelet.sock inode alone: tmpfs reuses inode numbers, so a fast restart
  // can leave it unchanged.)
  void CheckOwnSocket() {
    struct stat st;
    if (stat(socket_path_.c_str(), &st) == 0 && S_ISSOCK(st.st_mode)) return;
    fprintf(stderr,
            "tpud: endpoint socket %s disappeared (kubelet restart?); "
            "re-listening\n",
            socket_path_.c_str());
    server_.Shutdown();
    watchers_.clear();  // streams died with their connections
    if (!server_.Listen(socket_path_)) {
      fprintf(stderr, "tpud: re-listen failed: %s\n", strerror(errno));
    }
    registered_ = false;
  }

  void MaybeRegister() {
    std::string kubelet_sock = opt_.kubelet_dir + "/kubelet.sock";
    struct stat st;
    if (stat(kubelet_sock.c_str(), &st) != 0) {
      registered_ = false;  // kubelet gone; re-register when it returns
      return;
    }
    bool same_socket =
        st.st_ino == kubelet_ino_ &&
        st.st_mtim.tv_sec == kubelet_mtim_.tv_sec &&
        st.st_mtim.tv_nsec == kubelet_mtim_.tv_nsec;
    if (registered_ && same_socket) return;

    v1beta1::RegisterRequest req;
    req.set_version("v1beta1");
    req.set_endpoint(opt_.endpoint);
    req.set_resource_name(opt_.resource);
    req.mutable_options()->set_get_preferred_allocation_available(true);
    std::string req_bytes;
    req.SerializeToString(&req_bytes);

    std::string resp_bytes;
    grpcmin::Status status;
    bool ok = grpcmin::Client::UnaryCall(
        kubelet_sock, "/v1beta1.Registration/Register", req_bytes,
        &resp_bytes, &status, 3000);
    if (ok && status.code == grpcmin::StatusCode::kOk) {
      registered_ = true;
      kubelet_ino_ = st.st_ino;
      kubelet_mtim_ = st.st_mtim;
      fprintf(stderr, "tpud: registered %s with kubelet (endpoint %s)\n",
              opt_.resource.c_str(), opt_.endpoint.c_str());
    } else if (!registered_) {
      fprintf(stderr, "tpud: kubelet registration failed (%s); will retry\n",
              status.message.empty() ? "transport error"
                                     : status.message.c_str());
    }
  }

  Options opt_;
  const tpud::AcceleratorType& acc_;
  grpcmin::Server server_;
  std::string socket_path_;
  std::vector<ChipDevice> devices_;
  tpud::ReservationTable reservations_;
  bool res_ok_ = false;
  std::string res_err_;
  struct timespec res_mtim_ = {0, 0};
  off_t res_size_ = -1;
  std::set<grpcmin::ServerStream*> watchers_;
  bool registered_ = false;
  ino_t kubelet_ino_ = 0;
  struct timespec kubelet_mtim_ = {0, 0};
};

bool ParseFlag(const char* arg, const char* name, std::string* out) {
  size_t n = strlen(name);
  if (strncmp(arg, name, n) == 0 && arg[n] == '=') {
    *out = arg + n + 1;
    return true;
  }
  return false;
}

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  std::string sval;
  for (int i = 1; i < argc; ++i) {
    const char* a = argv[i];
    if (ParseFlag(a, "--resource", &opt.resource)) continue;
    if (ParseFlag(a, "--accelerator", &opt.accelerator)) continue;
    if (ParseFlag(a, "--device-glob", &opt.device_glob)) continue;
    if (ParseFlag(a, "--libtpu-path", &opt.libtpu_path)) continue;
    if (ParseFlag(a, "--kubelet-dir", &opt.kubelet_dir)) continue;
    if (ParseFlag(a, "--endpoint", &opt.endpoint)) continue;
    if (ParseFlag(a, "--devfs-root", &opt.devfs_root)) continue;
    if (ParseFlag(a, "--reservations", &opt.reservations_path)) continue;
    if (ParseFlag(a, "--node-name", &opt.node_name)) continue;
    if (ParseFlag(a, "--fake-devices", &sval)) {
      opt.fake_devices = atoi(sval.c_str());
      continue;
    }
    if (ParseFlag(a, "--rescan-interval", &sval)) {
      opt.rescan_interval_s = atoi(sval.c_str());
      continue;
    }
    if (strcmp(a, "--no-register") == 0) {
      opt.do_register = false;
      continue;
    }
    if (strcmp(a, "--print-topology-golden") == 0) {
      opt.print_topology_golden = true;
      continue;
    }
    fprintf(stderr,
            "tpud: unknown flag %s\n"
            "usage: tpud [--resource=google.com/tpu] [--accelerator=v5e-8]\n"
            "            [--device-glob=/dev/accel*] [--devfs-root=DIR]\n"
            "            [--fake-devices=N] [--libtpu-path=PATH]\n"
            "            [--kubelet-dir=DIR] [--endpoint=tpud.sock]\n"
            "            [--rescan-interval=SECS] [--no-register]\n"
            "            [--reservations=PATH] [--node-name=NAME]\n"
            "            [--print-topology-golden]\n",
            a);
    return 2;
  }

  if (opt.print_topology_golden) {
    printf("%s\n", tpud::GoldenJson().c_str());
    return 0;
  }

  if (!opt.reservations_path.empty() && opt.node_name.empty()) {
    // reservation tables are keyed by Node name; a real deployment
    // injects it via the downward API, and the hostname is the sane
    // default on self-managed nodes (kubeadm registers nodes by it)
    char host[256] = {0};
    if (gethostname(host, sizeof(host) - 1) == 0) opt.node_name = host;
    if (opt.node_name.empty()) {
      fprintf(stderr, "tpud: --reservations needs --node-name (hostname "
              "lookup failed)\n");
      return 2;
    }
  }

  const tpud::AcceleratorType* acc = tpud::FindAccelerator(opt.accelerator);
  if (!acc) {
    fprintf(stderr, "tpud: unknown accelerator type '%s'; known:",
            opt.accelerator.c_str());
    for (const auto& n : tpud::KnownAccelerators())
      fprintf(stderr, " %s", n.c_str());
    fprintf(stderr, "\n");
    return 2;
  }

  signal(SIGINT, HandleSignal);
  signal(SIGTERM, HandleSignal);
  signal(SIGPIPE, SIG_IGN);

  Plugin plugin(opt, *acc);
  if (!plugin.Init()) return 1;
  plugin.Run();
  return 0;
}
