#include "h2.h"

#include <errno.h>
#include <string.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>

namespace grpcmin {

namespace {

const char kClientMagic[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kMagicLen = 24;

uint32_t ReadU32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

void PutU32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}

}  // namespace

H2Conn::H2Conn(int fd, Role role)
    : fd_(fd), role_(role), next_stream_id_(1) {}

H2Conn::~H2Conn() {
  if (fd_ >= 0) close(fd_);
}

bool H2Conn::Start() {
  if (role_ == Role::kClient) {
    if (!WriteRaw(reinterpret_cast<const uint8_t*>(kClientMagic), kMagicLen))
      return false;
  }
  // SETTINGS: HEADER_TABLE_SIZE=4096, INITIAL_WINDOW_SIZE, MAX_FRAME_SIZE.
  uint8_t s[18];
  s[0] = 0; s[1] = 0x1; PutU32(s + 2, 4096);
  s[6] = 0; s[7] = 0x4; PutU32(s + 8, kOurInitialWindow);
  s[12] = 0; s[13] = 0x5; PutU32(s + 14, kMaxFrameSize);
  if (!WriteFrame(FrameType::kSettings, 0, 0, s, sizeof(s))) return false;
  // Grow the connection-level receive window up front so we never stall the
  // peer; we also replenish per-DATA below.
  uint8_t w[4];
  PutU32(w, kOurInitialWindow - kDefaultWindow);
  return WriteFrame(FrameType::kWindowUpdate, 0, 0, w, 4);
}

bool H2Conn::WriteRaw(const uint8_t* data, size_t len) {
  if (!alive_) return false;
  wbuf_.append(reinterpret_cast<const char*>(data), len);
  return Flush();
}

bool H2Conn::Flush() {
  while (!wbuf_.empty()) {
    ssize_t n = write(fd_, wbuf_.data(), wbuf_.size());
    if (n > 0) {
      wbuf_.erase(0, static_cast<size_t>(n));
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return true;  // try again when writable
    } else if (n < 0 && errno == EINTR) {
      continue;
    } else {
      alive_ = false;
      return false;
    }
  }
  return true;
}

bool H2Conn::WriteFrame(FrameType type, uint8_t flags, uint32_t stream_id,
                        const uint8_t* payload, size_t len) {
  uint8_t hdr[9];
  hdr[0] = (len >> 16) & 0xff; hdr[1] = (len >> 8) & 0xff; hdr[2] = len & 0xff;
  hdr[3] = static_cast<uint8_t>(type);
  hdr[4] = flags;
  PutU32(hdr + 5, stream_id & 0x7fffffff);
  if (!alive_) return false;
  wbuf_.append(reinterpret_cast<const char*>(hdr), 9);
  if (len) wbuf_.append(reinterpret_cast<const char*>(payload), len);
  return Flush();
}

uint32_t H2Conn::NextStreamId() {
  uint32_t id = next_stream_id_;
  next_stream_id_ += 2;
  streams_[id] = std::make_unique<H2Stream>();
  streams_[id]->id = id;
  streams_[id]->send_window = peer_initial_window_;
  return id;
}

H2Stream* H2Conn::GetStream(uint32_t id) {
  auto it = streams_.find(id);
  return it == streams_.end() ? nullptr : it->second.get();
}

void H2Conn::ForgetStream(uint32_t id) {
  // Deferred destruction: callbacks (on_data/on_headers) run while the
  // frame-processing path still holds a raw H2Stream*, and they may call
  // ForgetStream (a unary handler finishing). Unlink the stream now so
  // GetStream stops returning it, but free it only at ReapDoomed(), a
  // point where no raw pointer is live.
  auto it = streams_.find(id);
  if (it == streams_.end()) return;
  doomed_.push_back(std::move(it->second));
  streams_.erase(it);
}

void H2Conn::ReapDoomed() { doomed_.clear(); }

void H2Conn::PumpAllPending() {
  // Snapshot ids first: PumpPending can close a stream, whose
  // on_stream_closed may ForgetStream — erasing from streams_ mid-iteration
  // would invalidate a range-for.
  std::vector<uint32_t> ids;
  ids.reserve(streams_.size());
  for (auto& [sid, s] : streams_) ids.push_back(sid);
  for (uint32_t sid : ids) {
    H2Stream* s = GetStream(sid);
    if (s) PumpPending(s);
  }
}

bool H2Conn::SendHeaders(uint32_t stream_id, const std::vector<Header>& headers,
                         bool end_stream) {
  std::vector<uint8_t> block;
  HpackEncoder::EncodeAll(headers, &block);
  uint8_t flags = kFlagEndHeaders | (end_stream ? kFlagEndStream : 0);
  if (block.size() > peer_max_frame_) return false;  // we never come close
  H2Stream* s = GetStream(stream_id);
  if (s && end_stream) s->local_closed = true;
  bool ok = WriteFrame(FrameType::kHeaders, flags, stream_id, block.data(),
                       block.size());
  if (s) CloseStreamIfDone(s);
  return ok;
}

void H2Conn::PumpPending(H2Stream* s) {
  while (!s->pending_send.empty() && conn_send_window_ > 0 &&
         s->send_window > 0) {
    size_t chunk = s->pending_send.size();
    chunk = std::min<size_t>(chunk, static_cast<size_t>(conn_send_window_));
    chunk = std::min<size_t>(chunk, static_cast<size_t>(s->send_window));
    chunk = std::min<size_t>(chunk, peer_max_frame_);
    bool last = chunk == s->pending_send.size();
    uint8_t flags = (last && s->pending_end_stream) ? kFlagEndStream : 0;
    if (!WriteFrame(FrameType::kData, flags, s->id,
                    reinterpret_cast<const uint8_t*>(s->pending_send.data()),
                    chunk))
      return;
    conn_send_window_ -= chunk;
    s->send_window -= chunk;
    s->pending_send.erase(0, chunk);
    if (last && s->pending_end_stream) s->local_closed = true;
  }
  CloseStreamIfDone(s);
}

bool H2Conn::SendData(uint32_t stream_id, const std::string& payload,
                      bool end_stream) {
  H2Stream* s = GetStream(stream_id);
  if (!s || s->reset || s->local_closed) return false;
  s->pending_send += payload;
  s->pending_end_stream = s->pending_end_stream || end_stream;
  if (end_stream && payload.empty() && s->pending_send.empty()) {
    // Bare half-close: empty DATA with END_STREAM.
    bool ok = WriteFrame(FrameType::kData, kFlagEndStream, stream_id,
                         nullptr, 0);
    s->local_closed = true;
    CloseStreamIfDone(s);
    return ok;
  }
  PumpPending(s);
  return alive_;
}

bool H2Conn::SendRstStream(uint32_t stream_id, uint32_t error_code) {
  uint8_t p[4];
  PutU32(p, error_code);
  H2Stream* s = GetStream(stream_id);
  if (s) s->reset = true;
  return WriteFrame(FrameType::kRstStream, 0, stream_id, p, 4);
}

bool H2Conn::SendGoAway(uint32_t error_code) {
  uint8_t p[8];
  PutU32(p, 0);  // last stream id — we don't resume, 0 is conservative
  PutU32(p + 4, error_code);
  return WriteFrame(FrameType::kGoAway, 0, 0, p, 8);
}

bool H2Conn::SendPingAck(const uint8_t* opaque) {
  return WriteFrame(FrameType::kPing, kFlagAck, 0, opaque, 8);
}

void H2Conn::CloseStreamIfDone(H2Stream* s) {
  if ((s->remote_closed && s->local_closed && s->pending_send.empty()) ||
      s->reset) {
    if (on_stream_closed) on_stream_closed(s);
    // The gRPC layer calls ForgetStream when it is done with user state.
  }
}

bool H2Conn::OnReadable() {
  // Free streams doomed during the previous cycle: no raw H2Stream*
  // survives across OnReadable calls.
  ReapDoomed();
  char buf[16384];
  while (alive_) {
    ssize_t n = read(fd_, buf, sizeof(buf));
    if (n > 0) {
      rbuf_.append(buf, static_cast<size_t>(n));
      if (static_cast<size_t>(n) < sizeof(buf)) break;
    } else if (n == 0) {
      alive_ = false;
      return false;
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      break;
    } else if (errno == EINTR) {
      continue;
    } else {
      alive_ = false;
      return false;
    }
  }

  if (role_ == Role::kServer && !got_preface_) {
    if (rbuf_.size() < kMagicLen) return alive_;
    if (memcmp(rbuf_.data(), kClientMagic, kMagicLen) != 0) {
      alive_ = false;
      return false;
    }
    rbuf_.erase(0, kMagicLen);
    got_preface_ = true;
  }

  while (rbuf_.size() >= 9) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(rbuf_.data());
    size_t len = (size_t(p[0]) << 16) | (size_t(p[1]) << 8) | p[2];
    if (len > (1u << 24)) { alive_ = false; return false; }
    if (rbuf_.size() < 9 + len) break;
    uint8_t type = p[3], flags = p[4];
    uint32_t stream_id = ReadU32(p + 5) & 0x7fffffff;
    if (!ProcessFrame(type, flags, stream_id, p + 9, len)) {
      SendGoAway(0x1);  // PROTOCOL_ERROR
      alive_ = false;
      return false;
    }
    rbuf_.erase(0, 9 + len);
  }
  return alive_;
}

bool H2Conn::ProcessFrame(uint8_t type, uint8_t flags, uint32_t stream_id,
                          const uint8_t* payload, size_t len) {
  // A header block in flight only admits CONTINUATION for that stream.
  if (!hdr_block_.empty() || hdr_stream_) {
    if (type != static_cast<uint8_t>(FrameType::kContinuation) ||
        stream_id != hdr_stream_)
      return false;
  }
  switch (static_cast<FrameType>(type)) {
    case FrameType::kSettings:
      return HandleSettings(flags, payload, len);
    case FrameType::kPing:
      if (len != 8) return false;
      if (!(flags & kFlagAck)) return SendPingAck(payload);
      return true;
    case FrameType::kWindowUpdate:
      return HandleWindowUpdate(stream_id, payload, len);
    case FrameType::kGoAway:
      // Peer is going away; finish what we have. Mark not-alive on read EOF.
      return true;
    case FrameType::kPriority:
      return len == 5;
    case FrameType::kRstStream: {
      if (len != 4 || stream_id == 0) return false;
      H2Stream* s = GetStream(stream_id);
      if (s) {
        s->reset = true;
        CloseStreamIfDone(s);
      }
      return true;
    }
    case FrameType::kHeaders:
      return HandleHeaders(stream_id, flags, payload, len);
    case FrameType::kContinuation: {
      if (stream_id == 0 || stream_id != hdr_stream_) return false;
      hdr_block_.append(reinterpret_cast<const char*>(payload), len);
      if (flags & kFlagEndHeaders) return HeaderBlockComplete();
      return true;
    }
    case FrameType::kData: {
      if (stream_id == 0) return false;
      H2Stream* s = GetStream(stream_id);
      size_t data_len = len;
      const uint8_t* data = payload;
      if (flags & kFlagPadded) {
        if (len < 1) return false;
        uint8_t pad = payload[0];
        if (pad + 1u > len) return false;
        data = payload + 1;
        data_len = len - 1 - pad;
      }
      // Replenish receive windows immediately (credit-based).
      if (len > 0) {
        uint8_t w[4];
        PutU32(w, static_cast<uint32_t>(len));
        WriteFrame(FrameType::kWindowUpdate, 0, 0, w, 4);
        if (s && !(flags & kFlagEndStream))
          WriteFrame(FrameType::kWindowUpdate, 0, stream_id, w, 4);
      }
      if (!s || s->reset) return true;  // ignore data for unknown streams
      bool end = flags & kFlagEndStream;
      if (end) s->remote_closed = true;
      if (on_data) on_data(s, data, data_len, end);
      CloseStreamIfDone(s);
      return true;
    }
    case FrameType::kPushPromise:
      return false;  // we never enable push
    default:
      return true;  // ignore unknown frame types (spec requirement)
  }
}

bool H2Conn::HandleHeaders(uint32_t stream_id, uint8_t flags,
                           const uint8_t* frag, size_t len) {
  if (stream_id == 0) return false;
  size_t off = 0;
  if (flags & kFlagPadded) {
    if (len < 1) return false;
    uint8_t pad = frag[0];
    off = 1;
    if (off + pad > len) return false;
    len -= pad;
  }
  if (flags & kFlagPriority) {
    if (len < off + 5) return false;
    off += 5;
  }
  H2Stream* s = GetStream(stream_id);
  if (!s) {
    if (role_ == Role::kServer) {
      auto ns = std::make_unique<H2Stream>();
      ns->id = stream_id;
      ns->send_window = peer_initial_window_;
      s = ns.get();
      streams_[stream_id] = std::move(ns);
    } else {
      return false;  // server never opens streams toward us
    }
  }
  hdr_stream_ = stream_id;
  hdr_block_.assign(reinterpret_cast<const char*>(frag + off), len - off);
  hdr_end_stream_ = flags & kFlagEndStream;
  if (flags & kFlagEndHeaders) return HeaderBlockComplete();
  return true;
}

bool H2Conn::HeaderBlockComplete() {
  uint32_t sid = hdr_stream_;
  hdr_stream_ = 0;
  H2Stream* s = GetStream(sid);
  std::vector<Header> headers;
  bool ok = hpack_.Decode(
      reinterpret_cast<const uint8_t*>(hdr_block_.data()), hdr_block_.size(),
      &headers);
  hdr_block_.clear();
  if (!ok) return false;
  if (!s) return true;
  bool trailers = s->headers_done;
  if (trailers) {
    s->trailers = std::move(headers);
  } else {
    s->headers = std::move(headers);
    s->headers_done = true;
  }
  if (hdr_end_stream_) s->remote_closed = true;
  if (on_headers) on_headers(s, trailers);
  CloseStreamIfDone(s);
  return true;
}

bool H2Conn::HandleSettings(uint8_t flags, const uint8_t* payload, size_t len) {
  if (flags & kFlagAck) return len == 0;
  if (len % 6 != 0) return false;
  for (size_t i = 0; i < len; i += 6) {
    uint16_t id = (uint16_t(payload[i]) << 8) | payload[i + 1];
    uint32_t value = ReadU32(payload + i + 2);
    switch (id) {
      case 0x4: {  // INITIAL_WINDOW_SIZE: adjust all open stream windows
        if (value > 0x7fffffffu) return false;
        int64_t delta = int64_t(value) - int64_t(peer_initial_window_);
        peer_initial_window_ = value;
        for (auto& [sid, s] : streams_) {
          s->send_window += delta;
        }
        break;
      }
      case 0x5:
        if (value < 16384 || value > 16777215) return false;
        peer_max_frame_ = value;
        break;
      default:
        break;  // header table size handled implicitly (we never index)
    }
  }
  got_peer_settings_ = true;
  if (!WriteFrame(FrameType::kSettings, kFlagAck, 0, nullptr, 0)) return false;
  // New window may unblock pending sends.
  PumpAllPending();
  return true;
}

bool H2Conn::HandleWindowUpdate(uint32_t stream_id, const uint8_t* p,
                                size_t len) {
  if (len != 4) return false;
  uint32_t inc = ReadU32(p) & 0x7fffffff;
  if (inc == 0) return stream_id != 0;  // conn-level zero increment is fatal
  if (stream_id == 0) {
    conn_send_window_ += inc;
    PumpAllPending();
  } else {
    H2Stream* s = GetStream(stream_id);
    if (s) {
      s->send_window += inc;
      PumpPending(s);
    }
  }
  return true;
}

}  // namespace grpcmin
