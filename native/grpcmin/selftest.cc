// grpcmin unit selftest: HPACK integers, Huffman, full header blocks
// (vectors produced by an independent RFC 7541 implementation, exercising
// Huffman coding, static-table references and dynamic-table indexing), and
// gRPC message framing. Exit 0 on success; prints the first failure.
#include <fcntl.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc.h"
#include "h2.h"
#include "hpack.h"

using grpcmin::Header;
using grpcmin::HpackDecoder;
using grpcmin::HpackEncoder;

static int failures = 0;

#define CHECK(cond)                                              \
  do {                                                           \
    if (!(cond)) {                                               \
      printf("FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);     \
      ++failures;                                                \
    }                                                            \
  } while (0)

static std::vector<uint8_t> FromHex(const char* hex) {
  std::vector<uint8_t> out;
  for (size_t i = 0; hex[i] && hex[i + 1]; i += 2) {
    auto nib = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      if (c >= 'a' && c <= 'f') return c - 'a' + 10;
      return c - 'A' + 10;
    };
    out.push_back(uint8_t(nib(hex[i]) << 4 | nib(hex[i + 1])));
  }
  return out;
}

static void TestIntegers() {
  // RFC 7541 §C.1 examples.
  std::vector<uint8_t> buf;
  grpcmin::EncodeInt(10, 5, 0, &buf);
  CHECK(buf.size() == 1 && buf[0] == 0x0a);
  buf.clear();
  grpcmin::EncodeInt(1337, 5, 0, &buf);
  CHECK(buf.size() == 3 && buf[0] == 0x1f && buf[1] == 0x9a && buf[2] == 0x0a);
  buf.clear();
  grpcmin::EncodeInt(42, 8, 0, &buf);
  CHECK(buf.size() == 1 && buf[0] == 0x2a);

  size_t pos = 0;
  uint64_t v;
  uint8_t b1337[] = {0x1f, 0x9a, 0x0a};
  CHECK(grpcmin::DecodeInt(b1337, 3, &pos, 5, &v) && v == 1337 && pos == 3);
  // Truncated continuation must fail, not loop.
  pos = 0;
  uint8_t trunc[] = {0x1f, 0x9a};
  CHECK(!grpcmin::DecodeInt(trunc, 2, &pos, 5, &v));
}

static void TestHuffman() {
  // "www.example.com" Huffman-coded (RFC 7541 §C.4.1 string).
  auto bytes = FromHex("f1e3c2e5f23a6ba0ab90f4ff");
  std::string out;
  CHECK(grpcmin::HuffmanDecode(bytes.data(), bytes.size(), &out));
  CHECK(out == "www.example.com");
  // Bad padding (0 bits where EOS-prefix 1s required).
  auto bad = FromHex("f1e3c2e5f23a6ba0ab90f400");
  out.clear();
  CHECK(!grpcmin::HuffmanDecode(bad.data(), bad.size(), &out));
}

static void TestHeaderBlocks() {
  // Two consecutive blocks from one grpc-style encoder connection:
  // huffman strings + incremental indexing + dynamic-table hits in block 2.
  const char* v1 =
      "8386449963b8632a4615ef97b9885d745b31aa633990986a9390d249ff4186a0e41d13"
      "9d095f8b1d75d0620d263d4c4d65647a8a9acac8b4c7602bb825c14082497f864d8335"
      "05b11f";
  const char* v2 =
      "8386449663b8632a4615ef97b9885d745b31aa621a28390692ffc2c1c0bf40899acac8"
      "b24d494f6a7f846400053f";
  HpackDecoder dec;
  auto b1 = FromHex(v1);
  std::vector<Header> h1;
  CHECK(dec.Decode(b1.data(), b1.size(), &h1));
  CHECK(h1.size() == 7);
  auto find = [](const std::vector<Header>& hs, const char* k) {
    for (auto& [n, v] : hs)
      if (n == k) return v;
    return std::string("<missing>");
  };
  CHECK(find(h1, ":method") == "POST");
  CHECK(find(h1, ":scheme") == "http");
  CHECK(find(h1, ":path") == "/v1beta1.DevicePlugin/ListAndWatch");
  CHECK(find(h1, ":authority") == "localhost");
  CHECK(find(h1, "content-type") == "application/grpc");
  CHECK(find(h1, "user-agent") == "grpc-go/1.62.0");
  CHECK(find(h1, "te") == "trailers");

  auto b2 = FromHex(v2);
  std::vector<Header> h2;
  CHECK(dec.Decode(b2.data(), b2.size(), &h2));
  CHECK(h2.size() == 8);
  CHECK(find(h2, ":path") == "/v1beta1.DevicePlugin/Allocate");
  CHECK(find(h2, ":authority") == "localhost");   // dynamic-table hit
  CHECK(find(h2, "user-agent") == "grpc-go/1.62.0");
  CHECK(find(h2, "grpc-timeout") == "3000m");
}

static void TestEncoderRoundTrip() {
  std::vector<Header> hs = {{":status", "200"},
                            {"content-type", "application/grpc"},
                            {"grpc-status", "0"}};
  std::vector<uint8_t> buf;
  HpackEncoder::EncodeAll(hs, &buf);
  HpackDecoder dec;
  std::vector<Header> out;
  CHECK(dec.Decode(buf.data(), buf.size(), &out));
  CHECK(out == hs);
}

static void TestFraming() {
  std::string framed = grpcmin::FrameMessage("hello");
  CHECK(framed.size() == 10 && framed[0] == 0 && framed[4] == 5);
  std::string buf = framed + grpcmin::FrameMessage("");
  std::string msg;
  bool bad;
  CHECK(grpcmin::UnframeMessage(&buf, &msg, &bad) && msg == "hello" && !bad);
  CHECK(grpcmin::UnframeMessage(&buf, &msg, &bad) && msg.empty() && !bad);
  CHECK(buf.empty());
  // Compressed flag set -> bad.
  buf = std::string("\x01\x00\x00\x00\x00", 5);
  CHECK(!grpcmin::UnframeMessage(&buf, &msg, &bad) && bad);
  // Partial message -> incomplete, not bad.
  buf = std::string("\x00\x00\x00\x00\x05he", 7);
  CHECK(!grpcmin::UnframeMessage(&buf, &msg, &bad) && !bad);
}

// --- deterministic fuzz: the wire-facing parsers must reject arbitrary
// bytes without crashing or reading out of bounds (the CI ASan build of
// this selftest is the memory oracle; kubelet is a trusted peer, but a
// restarting/half-written socket still delivers torn frames). Seeded LCG,
// so a failure reproduces exactly.

static uint32_t g_lcg;
static uint32_t Rnd() {
  g_lcg = g_lcg * 1664525u + 1013904223u;
  return g_lcg >> 8;
}

static void TestHpackDecoderFuzz() {
  for (uint32_t seed = 1; seed <= 2000; ++seed) {
    g_lcg = seed;
    std::vector<uint8_t> buf(Rnd() % 96);
    for (auto& b : buf) b = uint8_t(Rnd());
    HpackDecoder dec(256);
    std::vector<Header> out;
    (void)dec.Decode(buf.data(), buf.size(), &out);
    // the decoder must stay usable after rejecting a malformed block
    std::vector<uint8_t> ok = FromHex("828684");  // 3 indexed static fields
    std::vector<Header> out2;
    CHECK(dec.Decode(ok.data(), ok.size(), &out2) && out2.size() == 3);
  }
}

// Feed a byte stream into a server-role H2Conn over a socketpair, draining
// whatever the connection queues back so neither side can block.
static void FeedH2(const std::string& bytes, bool with_preface) {
  int sv[2];
  CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  fcntl(sv[0], F_SETFL, O_NONBLOCK);
  fcntl(sv[1], F_SETFL, O_NONBLOCK);
  grpcmin::H2Conn conn(sv[0], grpcmin::H2Conn::Role::kServer);
  conn.Start();
  std::string all;
  if (with_preface) all = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
  all += bytes;
  size_t off = 0;
  bool live = true;
  while (off < all.size() && live) {
    size_t chunk = std::min<size_t>(2048, all.size() - off);
    ssize_t w = write(sv[1], all.data() + off, chunk);
    if (w <= 0) break;
    off += size_t(w);
    live = conn.OnReadable();
    char sink[8192];
    while (read(sv[1], sink, sizeof(sink)) > 0) {
    }
  }
  (void)conn.OnReadable();
  close(sv[1]);
}

static void TestH2ConnFuzz() {
  // raw garbage: dies at the preface check, never crashes
  for (uint32_t seed = 1; seed <= 64; ++seed) {
    g_lcg = seed;
    std::string bytes(Rnd() % 1024, '\0');
    for (auto& c : bytes) c = char(Rnd());
    FeedH2(bytes, /*with_preface=*/false);
  }
  // valid preface + random frames: exercises the frame dispatcher with
  // hostile types/flags/stream-ids/payloads (HEADERS land in HPACK too)
  for (uint32_t seed = 1; seed <= 256; ++seed) {
    g_lcg = seed;
    std::string bytes;
    int frames = 1 + int(Rnd() % 8);
    for (int i = 0; i < frames; ++i) {
      size_t len = Rnd() % 160;
      uint8_t type = uint8_t(Rnd() % 11);  // includes one unknown type
      uint8_t flags = uint8_t(Rnd());
      uint32_t stream = Rnd() % 7;
      uint8_t hdr[9] = {uint8_t(len >> 16), uint8_t(len >> 8), uint8_t(len),
                        type, flags, uint8_t(stream >> 24),
                        uint8_t(stream >> 16), uint8_t(stream >> 8),
                        uint8_t(stream)};
      bytes.append(reinterpret_cast<char*>(hdr), sizeof(hdr));
      for (size_t j = 0; j < len; ++j) bytes.push_back(char(Rnd()));
    }
    FeedH2(bytes, /*with_preface=*/true);
  }
}

int main() {
  TestIntegers();
  TestHuffman();
  TestHeaderBlocks();
  TestEncoderRoundTrip();
  TestFraming();
  TestHpackDecoderFuzz();
  TestH2ConnFuzz();
  if (failures == 0) {
    printf("grpcmin selftest: all OK\n");
    return 0;
  }
  printf("grpcmin selftest: %d failure(s)\n", failures);
  return 1;
}
