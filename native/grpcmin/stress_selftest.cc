// Threaded stress selftest — the native half of the concurrency
// correctness suite, built to run under ThreadSanitizer
// (-fsanitize=thread; CI's TSan job, plus scripts/asan_interop.py
// --tsan).
//
// The grpcmin/h2/hpack stack and the operator's minijson/kubeclient
// helpers all claim "single-threaded per connection, shared-nothing
// across threads" (h2.h header contract). Nothing enforced that: a
// lazily-initialized static table or a shared scratch buffer added to
// hpack would be invisible to the single-threaded selftests and surface
// as a production heisenbug inside the kubelet's grpc-go peer. This
// binary makes the claim testable — N threads drive private instances
// of every layer concurrently, so ANY hidden cross-thread mutable state
// becomes a TSan report with two stacks attached. A mutex+condvar work
// queue between producer and consumer threads exercises the
// synchronized path too (TSan validates the happy path as well as
// catching the races).
//
// Runs clean (and fast) without sanitizers as a plain pthread smoke —
// CMake builds it unconditionally and tests/test_native.py runs it.
#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/socket.h>
#include <unistd.h>

#include "grpc.h"
#include "h2.h"
#include "hpack.h"
#include "kubeclient.h"
#include "minijson.h"
#include "workqueue.h"

using grpcmin::Header;
using grpcmin::HpackDecoder;
using grpcmin::HpackEncoder;

static std::atomic<int> g_failures{0};

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::printf("FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);     \
      g_failures.fetch_add(1, std::memory_order_relaxed);             \
    }                                                                 \
  } while (0)

// Per-thread seeded LCG: the single-threaded selftest's generator is a
// GLOBAL (fine there, a data race here) — each worker owns its state.
struct Rng {
  uint32_t s;
  explicit Rng(uint32_t seed) : s(seed) {}
  uint32_t next() {
    s = s * 1664525u + 1013904223u;
    return s >> 8;
  }
};

// ---------------------------------------------------------------- HPACK

static void HpackRound(Rng* rng) {
  HpackDecoder dec(4096);
  for (int block = 0; block < 8; ++block) {
    std::vector<Header> in;
    int n = 1 + int(rng->next() % 6);
    for (int i = 0; i < n; ++i) {
      std::string name = "x-k" + std::to_string(rng->next() % 16);
      std::string value(rng->next() % 48, char('a' + rng->next() % 26));
      in.push_back({name, value});
    }
    std::vector<uint8_t> wire;
    HpackEncoder::EncodeAll(in, &wire);
    std::vector<Header> out;
    CHECK(dec.Decode(wire.data(), wire.size(), &out));
    CHECK(out == in);  // Header is a (name, value) pair
  }
  // hostile bytes must not corrupt a decoder another thread's twin is
  // using (they share NOTHING — that is the claim under test)
  std::vector<uint8_t> garbage(rng->next() % 96);
  for (auto& b : garbage) b = uint8_t(rng->next());
  HpackDecoder hostile(256);
  std::vector<Header> sink;
  (void)hostile.Decode(garbage.data(), garbage.size(), &sink);
}

// ------------------------------------------------------------------- H2

// One private server-role conn per call, fed random frames over a
// socketpair (the single-threaded selftest's fuzz shape, parallelized).
static void H2Round(Rng* rng) {
  int sv[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
    CHECK(false && "socketpair");
    return;
  }
  fcntl(sv[0], F_SETFL, O_NONBLOCK);
  fcntl(sv[1], F_SETFL, O_NONBLOCK);
  {
    grpcmin::H2Conn conn(sv[0], grpcmin::H2Conn::Role::kServer);
    conn.Start();
    std::string bytes = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
    int frames = 1 + int(rng->next() % 6);
    for (int i = 0; i < frames; ++i) {
      size_t len = rng->next() % 128;
      uint8_t type = uint8_t(rng->next() % 11);
      uint8_t flags = uint8_t(rng->next());
      uint32_t stream = rng->next() % 7;
      uint8_t hdr[9] = {uint8_t(len >> 16), uint8_t(len >> 8),
                        uint8_t(len),       type,
                        flags,              uint8_t(stream >> 24),
                        uint8_t(stream >> 16), uint8_t(stream >> 8),
                        uint8_t(stream)};
      bytes.append(reinterpret_cast<char*>(hdr), sizeof(hdr));
      for (size_t j = 0; j < len; ++j) bytes.push_back(char(rng->next()));
    }
    size_t off = 0;
    bool live = true;
    while (off < bytes.size() && live) {
      size_t chunk = std::min<size_t>(1024, bytes.size() - off);
      ssize_t w = write(sv[1], bytes.data() + off, chunk);
      if (w <= 0) break;
      off += size_t(w);
      live = conn.OnReadable();
      char sink[8192];
      while (read(sv[1], sink, sizeof(sink)) > 0) {
      }
    }
    (void)conn.OnReadable();
  }  // conn closes sv[0]
  close(sv[1]);
}

// -------------------------------------------------------- minijson + kube

static void JsonRound(Rng* rng) {
  // build -> dump -> parse -> spot-check, all thread-private
  auto obj = minijson::Value::MakeObject();
  obj->Set("kind", std::make_shared<minijson::Value>(std::string("Test")));
  auto status = minijson::Value::MakeObject();
  double ready = double(rng->next() % 100);
  status->Set("numberReady",
              std::make_shared<minijson::Value>(ready));
  obj->Set("status", status);
  auto arr = minijson::Value::MakeArray();
  for (int i = 0; i < int(rng->next() % 5); ++i) {
    arr->Append(std::make_shared<minijson::Value>(double(i)));
  }
  obj->Set("items", arr);
  std::string text = obj->Dump();
  std::string err;
  auto back = minijson::Parse(text, &err);
  CHECK(back != nullptr);
  if (back) {
    CHECK(back->PathNumber("status.numberReady", -1) == ready);
    CHECK(back->PathString("kind") == "Test");
  }
  // malformed input: parser must fail cleanly, thread-locally
  auto broken = minijson::Parse("{\"unterminated\": ", &err);
  CHECK(broken == nullptr && !err.empty());
  // the retry taxonomy + backoff pacing are pure functions — hammer
  // them concurrently so an accidental static cache would trip TSan
  CHECK(kubeclient::RetryableStatus(503));
  CHECK(!kubeclient::RetryableStatus(404));
  int ms = kubeclient::WatchBackoffMs(1 + int(rng->next() % 6), 100, 2000);
  CHECK(ms >= 0 && ms <= 2000);
}

// --------------------------------------------- shared mutex/condvar queue

struct WorkQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::string> items;
  bool done = false;
};

static void Producer(WorkQueue* q, int id, int rounds) {
  Rng rng(uint32_t(1000 + id));
  for (int i = 0; i < rounds; ++i) {
    auto obj = minijson::Value::MakeObject();
    obj->Set("producer", std::make_shared<minijson::Value>(double(id)));
    obj->Set("seq", std::make_shared<minijson::Value>(double(i)));
    std::string doc = obj->Dump();
    {
      std::lock_guard<std::mutex> hold(q->mu);
      q->items.push_back(doc);
    }
    q->cv.notify_one();
  }
}

static int Consumer(WorkQueue* q) {
  int consumed = 0;
  for (;;) {
    std::string doc;
    {
      std::unique_lock<std::mutex> hold(q->mu);
      q->cv.wait(hold, [q] { return !q->items.empty() || q->done; });
      if (q->items.empty()) return consumed;
      doc = q->items.front();
      q->items.pop_front();
    }
    std::string err;
    auto v = minijson::Parse(doc, &err);
    CHECK(v != nullptr && v->PathNumber("seq", -1) >= 0);
    ++consumed;
  }
}

// ------------------------------------------------------------------ main

int main(int argc, char** argv) {
  int threads = 8;
  int rounds = 20;
  for (int i = 1; i < argc; ++i) {
    if (std::strncmp(argv[i], "--threads=", 10) == 0)
      threads = std::atoi(argv[i] + 10);
    if (std::strncmp(argv[i], "--rounds=", 9) == 0)
      rounds = std::atoi(argv[i] + 9);
  }
  if (threads < 2) threads = 2;
  if (rounds < 1) rounds = 1;

  // phase 1: shared-nothing parallel hammer over every claimed
  // single-threaded layer
  {
    std::vector<std::thread> pool;
    for (int t = 0; t < threads; ++t) {
      pool.emplace_back([t, rounds] {
        Rng rng(uint32_t(1 + t));
        for (int r = 0; r < rounds; ++r) {
          HpackRound(&rng);
          H2Round(&rng);
          JsonRound(&rng);
        }
      });
    }
    for (auto& th : pool) th.join();
  }

  // phase 2: producers feeding one consumer through a locked queue —
  // the synchronized path TSan should bless
  {
    WorkQueue q;
    std::thread consumer_thread;
    int consumed = 0;
    consumer_thread = std::thread([&q, &consumed] {
      consumed = Consumer(&q);
    });
    std::vector<std::thread> producers;
    for (int t = 0; t < threads; ++t) {
      producers.emplace_back(Producer, &q, t, rounds);
    }
    for (auto& th : producers) th.join();
    {
      std::lock_guard<std::mutex> hold(q.mu);
      q.done = true;
    }
    q.cv.notify_all();
    consumer_thread.join();
    CHECK(consumed == threads * rounds);
  }

  // phase 3: the operator's rate-limited workqueue under real contention
  // — N producers Add/AddRateLimited a shared key space while M workers
  // Get/Done/Forget. Invariants: nothing handed out twice concurrently
  // (dedup + processing marks), nothing lost (every key that was ever
  // Add()ed while not processing is eventually delivered), counters
  // monotonic. The operator itself is single-threaded; this proves the
  // queue's locking is correct anyway (TSan chews on the same body).
  {
    // Heap-allocated, NOT a stack local: libstdc++'s std::mutex never
    // calls pthread_mutex_destroy (trivial destructor), so a stack slot
    // reused from phase 2's queue would alias its dead mutex in TSan's
    // metadata and report phantom double-locks. malloc/free resets the
    // shadow state.
    auto qp = std::make_unique<workqueue::RateLimitedQueue>(0, 1, 8);
    workqueue::RateLimitedQueue& q = *qp;
    const int kKeys = 32;
    std::atomic<int> delivered{0};
    std::atomic<int> busy{0};  // workers between Get and Done
    std::atomic<bool> stop{false};
    std::vector<std::atomic<int>> in_flight(kKeys);
    for (auto& f : in_flight) f.store(0);
    std::vector<std::thread> workers;
    int nworkers = std::max(2, threads / 2);
    for (int w = 0; w < nworkers; ++w) {
      workers.emplace_back([&, w] {
        Rng rng(uint32_t(7000 + w));
        std::string key;
        for (;;) {
          if (!q.Get(&key, 5)) {
            if (stop.load()) break;
            continue;
          }
          busy.fetch_add(1);
          int idx = std::atoi(key.c_str() + 1);
          // dedup + processing marks mean no two workers ever hold the
          // same key at once — the central correctness claim
          CHECK(in_flight[idx].fetch_add(1) == 0);
          if (rng.next() % 4 == 0)
            q.AddRateLimited(key);  // simulate a failed reconcile
          else
            q.Forget(key);
          CHECK(in_flight[idx].fetch_sub(1) == 1);
          q.Done(key);
          delivered.fetch_add(1);
          busy.fetch_sub(1);
        }
      });
    }
    std::vector<std::thread> adders;
    for (int t = 0; t < threads; ++t) {
      adders.emplace_back([&q, t, rounds] {
        Rng rng(uint32_t(9000 + t));
        for (int i = 0; i < rounds * 8; ++i)
          q.Add("k" + std::to_string(rng.next() % kKeys));
      });
    }
    for (auto& th : adders) th.join();
    // Drain: producers are done, so once no worker holds a key AND
    // nothing is queued or pending retry, the queue is provably empty
    // (busy read FIRST — an idle worker can't create retries).
    for (int spin = 0; spin < 5000; ++spin) {
      if (busy.load() == 0 && q.depth() == 0 && q.NextDelayMs() < 0) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
    stop.store(true);
    q.ShutDown();
    for (auto& th : workers) th.join();
    CHECK(delivered.load() > 0);
    CHECK(q.adds() >= (long long)threads * rounds * 8);
    CHECK(q.depth() == 0);
  }

  int failures = g_failures.load();
  if (failures == 0) {
    std::printf("concurrency stress selftest: all OK "
                "(%d threads x %d rounds)\n", threads, rounds);
    return 0;
  }
  std::printf("concurrency stress selftest: %d failure(s)\n", failures);
  return 1;
}
