// Minimal HTTP/2 (RFC 7540) connection for gRPC over unix sockets.
//
// Scope: exactly what a kubelet-facing device plugin needs — no TLS, no
// priorities, no push, no server-initiated streams. Both roles (we serve the
// DevicePlugin service to kubelet's grpc-go client, and we dial kubelet's
// Registration service as a client). Single-threaded: the owner runs a poll()
// loop and calls OnReadable/Flush; all callbacks fire on that thread.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "hpack.h"

namespace grpcmin {

enum class FrameType : uint8_t {
  kData = 0x0,
  kHeaders = 0x1,
  kPriority = 0x2,
  kRstStream = 0x3,
  kSettings = 0x4,
  kPushPromise = 0x5,
  kPing = 0x6,
  kGoAway = 0x7,
  kWindowUpdate = 0x8,
  kContinuation = 0x9,
};

constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;

constexpr uint32_t kDefaultWindow = 65535;
constexpr uint32_t kOurInitialWindow = 1 << 20;
constexpr uint32_t kMaxFrameSize = 16384;

// Per-stream state inside a connection.
struct H2Stream {
  uint32_t id = 0;
  std::vector<Header> headers;         // request (server role) or response
  std::vector<Header> trailers;
  std::string data;                    // accumulated DATA payload (recv)
  bool headers_done = false;
  bool remote_closed = false;          // peer sent END_STREAM
  bool local_closed = false;           // we sent END_STREAM
  bool reset = false;
  int64_t send_window = kDefaultWindow;
  std::string pending_send;            // DATA bytes waiting on flow control
  bool pending_end_stream = false;
  void* user = nullptr;                // owned by the gRPC layer
};

class H2Conn {
 public:
  enum class Role { kServer, kClient };

  // fd must be an open socket; the connection takes ownership (closes it).
  H2Conn(int fd, Role role);
  ~H2Conn();

  // Non-copyable.
  H2Conn(const H2Conn&) = delete;
  H2Conn& operator=(const H2Conn&) = delete;

  // Sends preface (client role) + our SETTINGS. Call once after construction.
  bool Start();

  // Drains readable bytes and dispatches complete frames. Returns false when
  // the connection is dead (EOF, protocol error) — caller should destroy.
  bool OnReadable();

  // Attempts to write queued bytes (for callers using non-blocking fds).
  bool Flush();

  // --- sending (any role) ---
  bool SendHeaders(uint32_t stream_id, const std::vector<Header>& headers,
                   bool end_stream);
  // Queues DATA (respecting flow control) — message bytes, not gRPC-framed.
  bool SendData(uint32_t stream_id, const std::string& payload,
                bool end_stream);
  bool SendRstStream(uint32_t stream_id, uint32_t error_code);
  bool SendGoAway(uint32_t error_code);
  bool SendPingAck(const uint8_t* opaque);

  // Client role: opens a new stream, returns its id (odd, increasing).
  uint32_t NextStreamId();

  H2Stream* GetStream(uint32_t id);
  // Unlinks the stream (GetStream -> nullptr) but defers the free until
  // ReapDoomed() — safe to call from inside on_data/on_headers callbacks.
  void ForgetStream(uint32_t id);
  void ReapDoomed();
  void PumpAllPending();

  int fd() const { return fd_; }
  bool alive() const { return alive_; }
  bool handshake_done() const { return got_peer_settings_; }

  // --- callbacks (set by the gRPC layer) ---
  // Fired when a header block completes (END_HEADERS). trailers=true when
  // this is a trailing block on an existing stream.
  std::function<void(H2Stream*, bool trailers)> on_headers;
  // Fired per DATA frame after window accounting. end_stream signals
  // half-close.
  std::function<void(H2Stream*, const uint8_t* data, size_t len,
                     bool end_stream)> on_data;
  std::function<void(H2Stream*)> on_stream_closed;  // reset or END_STREAM

 private:
  bool ProcessFrame(uint8_t type, uint8_t flags, uint32_t stream_id,
                    const uint8_t* payload, size_t len);
  bool HandleHeaders(uint32_t stream_id, uint8_t flags, const uint8_t* frag,
                     size_t len);
  bool HandleSettings(uint8_t flags, const uint8_t* payload, size_t len);
  bool HandleWindowUpdate(uint32_t stream_id, const uint8_t* p, size_t len);
  bool HeaderBlockComplete();
  bool WriteRaw(const uint8_t* data, size_t len);
  bool WriteFrame(FrameType type, uint8_t flags, uint32_t stream_id,
                  const uint8_t* payload, size_t len);
  void PumpPending(H2Stream* s);
  void CloseStreamIfDone(H2Stream* s);

  int fd_;
  Role role_;
  bool alive_ = true;
  bool got_preface_ = false;       // server role: client magic received
  bool got_peer_settings_ = false;
  uint32_t next_stream_id_;        // client role
  std::string rbuf_;               // unparsed inbound bytes
  std::string wbuf_;               // unwritten outbound bytes
  HpackDecoder hpack_;
  int64_t conn_send_window_ = kDefaultWindow;
  uint32_t peer_initial_window_ = kDefaultWindow;
  uint32_t peer_max_frame_ = kMaxFrameSize;
  // In-flight header block (HEADERS + CONTINUATIONs until END_HEADERS).
  uint32_t hdr_stream_ = 0;
  std::string hdr_block_;
  bool hdr_end_stream_ = false;
  std::map<uint32_t, std::unique_ptr<H2Stream>> streams_;
  std::vector<std::unique_ptr<H2Stream>> doomed_;  // see ForgetStream
};

}  // namespace grpcmin
