// Minimal gRPC-over-HTTP/2 server and client for unix domain sockets.
//
// Server: serves unary and server-streaming methods (what the kubelet
// DevicePlugin API needs); wire-compatible with grpc-go (kubelet) and grpcio
// (test harness) clients. Client: blocking unary calls (Registration).
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "h2.h"

namespace grpcmin {

// Canonical gRPC status codes (subset we use).
enum class StatusCode : int {
  kOk = 0,
  kUnknown = 2,
  kInvalidArgument = 3,
  kNotFound = 5,
  kResourceExhausted = 8,
  kFailedPrecondition = 9,
  kUnimplemented = 12,
  kInternal = 13,
  kUnavailable = 14,
};

struct Status {
  StatusCode code = StatusCode::kOk;
  std::string message;
  static Status Ok() { return {}; }
};

// Handle for one live server-streaming call. Owned by the server; user code
// keeps the pointer only until on_closed fires.
class ServerStream {
 public:
  ServerStream(H2Conn* conn, uint32_t stream_id)
      : conn_(conn), stream_id_(stream_id) {}

  // Sends one length-prefixed gRPC message. False if the stream is gone.
  bool Send(const std::string& message_bytes);
  // Ends the stream with trailers.
  void Finish(const Status& status);
  bool finished() const { return finished_; }
  uint32_t id() const { return stream_id_; }

  std::function<void()> on_closed;  // stream reset / conn death

 private:
  friend class Server;
  H2Conn* conn_;
  uint32_t stream_id_;
  bool started_ = false;  // response HEADERS sent
  bool finished_ = false;
};

using UnaryHandler =
    std::function<Status(const std::string& request, std::string* response)>;
using StreamingHandler =
    std::function<void(const std::string& request, ServerStream* stream)>;

class Server {
 public:
  ~Server();

  // Binds + listens on a unix socket path (unlinks stale socket first).
  bool Listen(const std::string& socket_path);

  void AddUnary(const std::string& method_path, UnaryHandler h) {
    unary_[method_path] = std::move(h);
  }
  void AddServerStreaming(const std::string& method_path, StreamingHandler h) {
    streaming_[method_path] = std::move(h);
  }

  // One poll iteration: accepts, reads, dispatches. timeout_ms < 0 blocks.
  // Returns false only on listener failure.
  bool RunOnce(int timeout_ms);

  const std::string& socket_path() const { return path_; }
  size_t connection_count() const { return conns_.size(); }
  void Shutdown();

 private:
  struct CallState {
    std::string method;
    std::string buffer;       // raw DATA bytes, gRPC-framed
    std::string message;      // first complete message
    bool have_message = false;
    bool dispatched = false;
    bool streaming = false;
    std::unique_ptr<ServerStream> stream;
  };
  struct ConnEntry {
    std::unique_ptr<H2Conn> conn;
    // CallState per stream id (owned here, pointed to by H2Stream::user).
    std::map<uint32_t, std::unique_ptr<CallState>> calls;
  };

  void SetupConn(ConnEntry* e);
  void OnHeaders(ConnEntry* e, H2Stream* s);
  void OnData(ConnEntry* e, H2Stream* s, const uint8_t* data, size_t len,
              bool end_stream);
  void MaybeDispatch(ConnEntry* e, H2Stream* s);
  void DropConn(size_t index);

  int listen_fd_ = -1;
  std::string path_;
  std::vector<std::unique_ptr<ConnEntry>> conns_;
  std::map<std::string, UnaryHandler> unary_;
  std::map<std::string, StreamingHandler> streaming_;
};

// gRPC length-prefixed message framing helpers.
std::string FrameMessage(const std::string& message_bytes);
// Extracts the next complete message from buf (erasing it). Returns false if
// incomplete. Sets *bad on malformed (compressed flag set — we don't support
// compression, per gRPC spec that's only valid with an encoding we'd have
// negotiated).
bool UnframeMessage(std::string* buf, std::string* out, bool* bad);

class Client {
 public:
  // Blocking unary call over a fresh connection (fine for Register, which
  // happens once per kubelet lifetime). Returns transport-level success;
  // gRPC-level status lands in *status.
  static bool UnaryCall(const std::string& socket_path,
                        const std::string& method_path,
                        const std::string& request_bytes,
                        std::string* response_bytes, Status* status,
                        int timeout_ms = 5000);
};

}  // namespace grpcmin
