#include "hpack.h"

#include <cstring>

#include "hpack_constants.h"

namespace grpcmin {

// ---------------------------------------------------------------- integers

bool DecodeInt(const uint8_t* data, size_t len, size_t* pos, int prefix_bits,
               uint64_t* out) {
  if (*pos >= len) return false;
  const uint64_t max_prefix = (1u << prefix_bits) - 1;
  uint64_t value = data[*pos] & max_prefix;
  ++*pos;
  if (value < max_prefix) {
    *out = value;
    return true;
  }
  uint64_t m = 0;
  while (true) {
    if (*pos >= len || m > 56) return false;  // overflow / truncated
    uint8_t b = data[*pos];
    ++*pos;
    value += static_cast<uint64_t>(b & 0x7f) << m;
    if (!(b & 0x80)) break;
    m += 7;
  }
  *out = value;
  return true;
}

void EncodeInt(uint64_t value, int prefix_bits, uint8_t first_byte_flags,
               std::vector<uint8_t>* out) {
  const uint64_t max_prefix = (1u << prefix_bits) - 1;
  if (value < max_prefix) {
    out->push_back(first_byte_flags | static_cast<uint8_t>(value));
    return;
  }
  out->push_back(first_byte_flags | static_cast<uint8_t>(max_prefix));
  value -= max_prefix;
  while (value >= 128) {
    out->push_back(static_cast<uint8_t>((value & 0x7f) | 0x80));
    value >>= 7;
  }
  out->push_back(static_cast<uint8_t>(value));
}

// ---------------------------------------------------------------- huffman

namespace {

// Bitwise decode tree over the 257-symbol canonical code. ~2*257 nodes.
struct HuffNode {
  int16_t child[2];  // index into node pool, -1 if absent
  int16_t symbol;    // >=0 leaf symbol, -1 internal
};

struct HuffTree {
  std::vector<HuffNode> nodes;
  HuffTree() {
    nodes.push_back({{-1, -1}, -1});
    for (int sym = 0; sym < 257; ++sym) {
      uint32_t code = kHuffCodes[sym].code;
      int bits = kHuffCodes[sym].bits;
      int cur = 0;
      for (int i = bits - 1; i >= 0; --i) {
        int b = (code >> i) & 1;
        if (nodes[cur].child[b] < 0) {
          nodes[cur].child[b] = static_cast<int16_t>(nodes.size());
          nodes.push_back({{-1, -1}, -1});
        }
        cur = nodes[cur].child[b];
      }
      nodes[cur].symbol = static_cast<int16_t>(sym);
    }
  }
};

const HuffTree& Tree() {
  static const HuffTree tree;
  return tree;
}

}  // namespace

bool HuffmanDecode(const uint8_t* data, size_t len, std::string* out) {
  const HuffTree& tree = Tree();
  int cur = 0;
  int depth = 0;  // bits consumed since last emitted symbol
  for (size_t i = 0; i < len; ++i) {
    for (int bit = 7; bit >= 0; --bit) {
      int b = (data[i] >> bit) & 1;
      int next = tree.nodes[cur].child[b];
      if (next < 0) return false;
      cur = next;
      ++depth;
      int sym = tree.nodes[cur].symbol;
      if (sym >= 0) {
        if (sym == 256) return false;  // EOS in stream is an error
        out->push_back(static_cast<char>(sym));
        cur = 0;
        depth = 0;
      }
    }
  }
  // Remaining bits must be a prefix of EOS (all ones), < 8 bits.
  if (depth >= 8) return false;
  // Walk the 1-branch from current node: every edge taken must exist and be 1.
  // Since padding is EOS-prefix (all 1 bits), validity == we never emitted and
  // all consumed padding bits were 1. We verify by checking the path we took
  // is along 1-bits only — which holds iff cur is reachable by all-ones.
  // Cheap check: re-walk depth ones from root.
  int check = 0;
  for (int i = 0; i < depth; ++i) {
    check = tree.nodes[check].child[1];
    if (check < 0) return false;
  }
  return check == cur;
}

// ---------------------------------------------------------------- decoder

bool HpackDecoder::LookupIndex(uint64_t index, Header* out) const {
  if (index == 0) return false;
  if (index <= kStaticTableSize) {
    const StaticEntry& e = kStaticTable[index - 1];
    *out = {e.name, e.value};
    return true;
  }
  size_t di = static_cast<size_t>(index - kStaticTableSize - 1);
  if (di >= dynamic_.size()) return false;
  *out = dynamic_[di];
  return true;
}

void HpackDecoder::EvictTo(size_t target) {
  while (dynamic_size_ > target && !dynamic_.empty()) {
    const Header& h = dynamic_.back();
    dynamic_size_ -= h.first.size() + h.second.size() + 32;
    dynamic_.pop_back();
  }
}

void HpackDecoder::InsertDynamic(Header h) {
  size_t sz = h.first.size() + h.second.size() + 32;
  if (sz > max_dynamic_size_) {
    // An entry larger than the table flushes it (RFC 7541 §4.4).
    EvictTo(0);
    return;
  }
  EvictTo(max_dynamic_size_ - sz);
  dynamic_.push_front(std::move(h));
  dynamic_size_ += sz;
}

namespace {

bool DecodeString(const uint8_t* data, size_t len, size_t* pos,
                  std::string* out) {
  if (*pos >= len) return false;
  bool huffman = (data[*pos] & 0x80) != 0;
  uint64_t slen;
  if (!DecodeInt(data, len, pos, 7, &slen)) return false;
  if (slen > len - *pos) return false;
  if (huffman) {
    if (!HuffmanDecode(data + *pos, slen, out)) return false;
  } else {
    out->assign(reinterpret_cast<const char*>(data + *pos), slen);
  }
  *pos += slen;
  return true;
}

}  // namespace

bool HpackDecoder::Decode(const uint8_t* data, size_t len,
                          std::vector<Header>* out) {
  size_t pos = 0;
  while (pos < len) {
    uint8_t b = data[pos];
    if (b & 0x80) {
      // Indexed header field.
      uint64_t idx;
      if (!DecodeInt(data, len, &pos, 7, &idx)) return false;
      Header h;
      if (!LookupIndex(idx, &h)) return false;
      out->push_back(std::move(h));
    } else if (b & 0x40) {
      // Literal with incremental indexing.
      uint64_t idx;
      if (!DecodeInt(data, len, &pos, 6, &idx)) return false;
      Header h;
      if (idx == 0) {
        if (!DecodeString(data, len, &pos, &h.first)) return false;
      } else {
        Header name_src;
        if (!LookupIndex(idx, &name_src)) return false;
        h.first = name_src.first;
      }
      if (!DecodeString(data, len, &pos, &h.second)) return false;
      out->push_back(h);
      InsertDynamic(std::move(h));
    } else if (b & 0x20) {
      // Dynamic table size update.
      uint64_t sz;
      if (!DecodeInt(data, len, &pos, 5, &sz)) return false;
      // We advertised SETTINGS_HEADER_TABLE_SIZE=4096; larger is an error.
      if (sz > 4096) return false;
      max_dynamic_size_ = static_cast<size_t>(sz);
      EvictTo(max_dynamic_size_);
    } else {
      // Literal without indexing (0x00) or never-indexed (0x10): same wire
      // shape, 4-bit prefix; we don't re-forward headers so the distinction
      // doesn't matter.
      uint64_t idx;
      if (!DecodeInt(data, len, &pos, 4, &idx)) return false;
      Header h;
      if (idx == 0) {
        if (!DecodeString(data, len, &pos, &h.first)) return false;
      } else {
        Header name_src;
        if (!LookupIndex(idx, &name_src)) return false;
        h.first = name_src.first;
      }
      if (!DecodeString(data, len, &pos, &h.second)) return false;
      out->push_back(std::move(h));
    }
  }
  return true;
}

// ---------------------------------------------------------------- encoder

void HpackEncoder::Encode(const Header& h, std::vector<uint8_t>* out) {
  out->push_back(0x00);  // literal without indexing, new name
  EncodeInt(h.first.size(), 7, 0x00, out);
  out->insert(out->end(), h.first.begin(), h.first.end());
  EncodeInt(h.second.size(), 7, 0x00, out);
  out->insert(out->end(), h.second.begin(), h.second.end());
}

void HpackEncoder::EncodeAll(const std::vector<Header>& hs,
                             std::vector<uint8_t>* out) {
  for (const Header& h : hs) Encode(h, out);
}

}  // namespace grpcmin
