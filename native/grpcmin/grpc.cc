#include "grpc.h"

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>

namespace grpcmin {

namespace {

bool SetNonBlocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags >= 0 && fcntl(fd, F_SETFL, flags | O_NONBLOCK) == 0;
}

int64_t NowMs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

std::string FindHeader(const std::vector<Header>& hs, const std::string& name) {
  for (const auto& [k, v] : hs)
    if (k == name) return v;
  return "";
}

std::vector<Header> ResponseHeaders() {
  return {{":status", "200"},
          {"content-type", "application/grpc"}};
}

std::vector<Header> Trailers(const Status& st) {
  std::vector<Header> t = {{"grpc-status", std::to_string(int(st.code))}};
  if (!st.message.empty()) t.push_back({"grpc-message", st.message});
  return t;
}

}  // namespace

// ------------------------------------------------------------- framing

std::string FrameMessage(const std::string& message_bytes) {
  std::string out;
  out.reserve(message_bytes.size() + 5);
  out.push_back('\0');  // no compression
  uint32_t n = static_cast<uint32_t>(message_bytes.size());
  out.push_back(char((n >> 24) & 0xff));
  out.push_back(char((n >> 16) & 0xff));
  out.push_back(char((n >> 8) & 0xff));
  out.push_back(char(n & 0xff));
  out += message_bytes;
  return out;
}

bool UnframeMessage(std::string* buf, std::string* out, bool* bad) {
  *bad = false;
  if (buf->size() < 5) return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(buf->data());
  if (p[0] != 0) {
    *bad = true;
    return false;
  }
  uint32_t n = (uint32_t(p[1]) << 24) | (uint32_t(p[2]) << 16) |
               (uint32_t(p[3]) << 8) | uint32_t(p[4]);
  if (buf->size() < 5u + n) return false;
  out->assign(*buf, 5, n);
  buf->erase(0, 5u + n);
  return true;
}

// ------------------------------------------------------------- ServerStream

bool ServerStream::Send(const std::string& message_bytes) {
  if (finished_ || !conn_ || !conn_->alive()) return false;
  H2Stream* s = conn_->GetStream(stream_id_);
  if (!s || s->reset) return false;
  if (!started_) {
    if (!conn_->SendHeaders(stream_id_, ResponseHeaders(), false)) return false;
    started_ = true;
  }
  return conn_->SendData(stream_id_, FrameMessage(message_bytes), false);
}

void ServerStream::Finish(const Status& status) {
  if (finished_) return;
  finished_ = true;
  if (!conn_ || !conn_->alive()) return;
  H2Stream* s = conn_->GetStream(stream_id_);
  if (!s || s->reset) return;
  if (!started_) {
    // Trailers-only response.
    auto hs = ResponseHeaders();
    for (auto& t : Trailers(status)) hs.push_back(t);
    conn_->SendHeaders(stream_id_, hs, true);
    return;
  }
  conn_->SendHeaders(stream_id_, Trailers(status), true);
}

// ------------------------------------------------------------- Server

Server::~Server() { Shutdown(); }

void Server::Shutdown() {
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
    if (!path_.empty()) unlink(path_.c_str());
  }
  conns_.clear();
}

bool Server::Listen(const std::string& socket_path) {
  path_ = socket_path;
  unlink(socket_path.c_str());
  listen_fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return false;
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  if (socket_path.size() >= sizeof(addr.sun_path)) return false;
  strncpy(addr.sun_path, socket_path.c_str(), sizeof(addr.sun_path) - 1);
  if (bind(listen_fd_, reinterpret_cast<struct sockaddr*>(&addr),
           sizeof(addr)) != 0)
    return false;
  if (listen(listen_fd_, 16) != 0) return false;
  return SetNonBlocking(listen_fd_);
}

void Server::SetupConn(ConnEntry* e) {
  H2Conn* c = e->conn.get();
  c->on_headers = [this, e](H2Stream* s, bool trailers) {
    if (!trailers) OnHeaders(e, s);
  };
  c->on_data = [this, e](H2Stream* s, const uint8_t* d, size_t n, bool end) {
    OnData(e, s, d, n, end);
  };
  c->on_stream_closed = [e](H2Stream* s) {
    auto it = e->calls.find(s->id);
    if (it != e->calls.end()) {
      CallState* cs = it->second.get();
      if (cs->stream && !cs->stream->finished()) {
        cs->stream->finished_ = true;
        if (cs->stream->on_closed) cs->stream->on_closed();
      }
      if (!cs->streaming || !cs->stream || cs->stream->finished()) {
        e->calls.erase(it);
        e->conn->ForgetStream(s->id);
      }
    } else {
      e->conn->ForgetStream(s->id);
    }
  };
}

void Server::OnHeaders(ConnEntry* e, H2Stream* s) {
  auto cs = std::make_unique<CallState>();
  cs->method = FindHeader(s->headers, ":path");
  s->user = cs.get();
  e->calls[s->id] = std::move(cs);
  MaybeDispatch(e, s);  // handles trailers-only / zero-arg dispatch on END
}

void Server::OnData(ConnEntry* e, H2Stream* s, const uint8_t* data, size_t len,
                    bool end_stream) {
  (void)end_stream;
  auto it = e->calls.find(s->id);
  if (it == e->calls.end()) return;
  CallState* cs = it->second.get();
  cs->buffer.append(reinterpret_cast<const char*>(data), len);
  if (!cs->have_message) {
    bool bad = false;
    if (UnframeMessage(&cs->buffer, &cs->message, &bad)) {
      cs->have_message = true;
    } else if (bad) {
      e->conn->SendRstStream(s->id, 0x1);
      return;
    }
  }
  MaybeDispatch(e, s);
}

void Server::MaybeDispatch(ConnEntry* e, H2Stream* s) {
  auto it = e->calls.find(s->id);
  if (it == e->calls.end()) return;
  CallState* cs = it->second.get();
  if (cs->dispatched) return;
  // Dispatch once the request message is complete. For methods whose request
  // is an empty proto (ListAndWatch!), the message is 5 zero bytes — still a
  // DATA frame, so have_message flips there. Guard with remote_closed for
  // clients that half-close without data.
  if (!cs->have_message && !s->remote_closed) return;
  cs->dispatched = true;

  auto su = streaming_.find(cs->method);
  if (su != streaming_.end()) {
    cs->streaming = true;
    cs->stream = std::make_unique<ServerStream>(e->conn.get(), s->id);
    su->second(cs->message, cs->stream.get());
    return;
  }
  auto uu = unary_.find(cs->method);
  if (uu == unary_.end()) {
    auto hs = ResponseHeaders();
    for (auto& t :
         Trailers({StatusCode::kUnimplemented, "unknown method " + cs->method}))
      hs.push_back(t);
    e->conn->SendHeaders(s->id, hs, true);
    return;
  }
  std::string response;
  Status st = uu->second(cs->message, &response);
  if (st.code != StatusCode::kOk) {
    auto hs = ResponseHeaders();
    for (auto& t : Trailers(st)) hs.push_back(t);
    e->conn->SendHeaders(s->id, hs, true);
    return;
  }
  e->conn->SendHeaders(s->id, ResponseHeaders(), false);
  e->conn->SendData(s->id, FrameMessage(response), false);
  e->conn->SendHeaders(s->id, Trailers(st), true);
}

void Server::DropConn(size_t index) {
  // Notify any live streams on this connection.
  for (auto& [sid, cs] : conns_[index]->calls) {
    if (cs->stream && !cs->stream->finished()) {
      cs->stream->finished_ = true;
      if (cs->stream->on_closed) cs->stream->on_closed();
    }
  }
  conns_.erase(conns_.begin() + index);
}

bool Server::RunOnce(int timeout_ms) {
  if (listen_fd_ < 0) return false;
  std::vector<struct pollfd> pfds;
  pfds.push_back({listen_fd_, POLLIN, 0});
  for (auto& e : conns_) {
    short events = POLLIN;
    pfds.push_back({e->conn->fd(), events, 0});
  }
  int rc = poll(pfds.data(), pfds.size(), timeout_ms);
  if (rc < 0) return errno == EINTR;
  if (rc == 0) return true;

  if (pfds[0].revents & POLLIN) {
    while (true) {
      int cfd = accept(listen_fd_, nullptr, nullptr);
      if (cfd < 0) break;
      SetNonBlocking(cfd);
      auto e = std::make_unique<ConnEntry>();
      e->conn = std::make_unique<H2Conn>(cfd, H2Conn::Role::kServer);
      SetupConn(e.get());
      if (e->conn->Start()) conns_.push_back(std::move(e));
    }
  }
  // Walk backwards so DropConn doesn't disturb earlier indices.
  for (size_t i = conns_.size(); i-- > 0;) {
    size_t pi = i + 1;
    if (pi >= pfds.size()) continue;
    if (pfds[pi].revents & (POLLIN | POLLHUP | POLLERR)) {
      if (!conns_[i]->conn->OnReadable()) {
        DropConn(i);
        continue;
      }
    }
    conns_[i]->conn->Flush();
  }
  return true;
}

// ------------------------------------------------------------- Client

bool Client::UnaryCall(const std::string& socket_path,
                       const std::string& method_path,
                       const std::string& request_bytes,
                       std::string* response_bytes, Status* status,
                       int timeout_ms) {
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return false;
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  if (socket_path.size() >= sizeof(addr.sun_path)) {
    close(fd);
    return false;
  }
  strncpy(addr.sun_path, socket_path.c_str(), sizeof(addr.sun_path) - 1);
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) !=
      0) {
    close(fd);
    return false;
  }
  SetNonBlocking(fd);

  H2Conn conn(fd, H2Conn::Role::kClient);
  bool done = false, ok = false;
  std::string data_buf;

  conn.on_headers = [&](H2Stream* s, bool trailers) {
    const std::vector<Header>& hs = trailers ? s->trailers : s->headers;
    std::string gs = FindHeader(hs, "grpc-status");
    if (!gs.empty()) {
      status->code = static_cast<StatusCode>(atoi(gs.c_str()));
      status->message = FindHeader(hs, "grpc-message");
      done = true;
      ok = true;
    }
  };
  conn.on_data = [&](H2Stream* s, const uint8_t* d, size_t n, bool end) {
    (void)s;
    (void)end;
    data_buf.append(reinterpret_cast<const char*>(d), n);
  };

  if (!conn.Start()) return false;
  uint32_t sid = conn.NextStreamId();
  std::vector<Header> req_headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", method_path},
      {":authority", "localhost"},
      {"content-type", "application/grpc"},
      {"te", "trailers"},
      {"user-agent", "grpcmin/0.1"},
  };
  if (!conn.SendHeaders(sid, req_headers, false)) return false;
  if (!conn.SendData(sid, FrameMessage(request_bytes), true)) return false;

  int64_t deadline = NowMs() + timeout_ms;
  while (!done && conn.alive()) {
    int64_t left = deadline - NowMs();
    if (left <= 0) {
      status->code = StatusCode::kUnavailable;
      status->message = "deadline exceeded waiting for response";
      return false;
    }
    struct pollfd pfd = {conn.fd(), POLLIN, 0};
    int rc = poll(&pfd, 1, static_cast<int>(std::min<int64_t>(left, 100)));
    if (rc < 0 && errno != EINTR) break;
    if (rc > 0 && (pfd.revents & (POLLIN | POLLHUP | POLLERR))) {
      if (!conn.OnReadable()) break;
    }
    conn.Flush();
  }
  if (!done) {
    status->code = StatusCode::kUnavailable;
    status->message = "connection closed before response";
    return false;
  }
  if (response_bytes) {
    bool bad = false;
    std::string msg;
    if (UnframeMessage(&data_buf, &msg, &bad)) *response_bytes = msg;
  }
  return ok;
}

}  // namespace grpcmin
