// HPACK (RFC 7541) header compression for the minimal gRPC transport.
//
// Decoder: full spec — indexed fields against the static + dynamic tables,
// all literal forms, dynamic-table size updates, and Huffman-coded strings
// (grpc-go and grpc C-core Huffman-encode header values, so a compliant
// decoder is mandatory for kubelet interop).
// Encoder: deliberately minimal — literal-without-indexing with raw (non-
// Huffman) strings, which is always legal and keeps us stateless on send.
#pragma once

#include <cstdint>
#include <deque>
#include <string>
#include <utility>
#include <vector>

namespace grpcmin {

using Header = std::pair<std::string, std::string>;

class HpackDecoder {
 public:
  explicit HpackDecoder(size_t max_dynamic_size = 4096)
      : max_dynamic_size_(max_dynamic_size), dynamic_size_(0) {}

  // Decodes one complete header block. Returns false on malformed input
  // (connection error COMPRESSION_ERROR per RFC 7540 §4.3).
  bool Decode(const uint8_t* data, size_t len, std::vector<Header>* out);

 private:
  bool LookupIndex(uint64_t index, Header* out) const;
  void InsertDynamic(Header h);
  void EvictTo(size_t target);

  size_t max_dynamic_size_;
  size_t dynamic_size_;
  std::deque<Header> dynamic_;  // front = most recent (index 62)
};

class HpackEncoder {
 public:
  // Appends the encoding of one header as literal-without-indexing.
  static void Encode(const Header& h, std::vector<uint8_t>* out);
  static void EncodeAll(const std::vector<Header>& hs,
                        std::vector<uint8_t>* out);
};

// Huffman decode over the RFC 7541 Appendix B code. Returns false on invalid
// padding / EOS in stream.
bool HuffmanDecode(const uint8_t* data, size_t len, std::string* out);

// Variable-length integer with n-bit prefix (RFC 7541 §5.1). Reads from
// data[*pos..len); *pos advances past the integer. prefix_bits in [1,8];
// first_byte_mask extracts the prefix from data[*pos].
bool DecodeInt(const uint8_t* data, size_t len, size_t* pos, int prefix_bits,
               uint64_t* out);
void EncodeInt(uint64_t value, int prefix_bits, uint8_t first_byte_flags,
               std::vector<uint8_t>* out);

}  // namespace grpcmin
