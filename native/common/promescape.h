// promescape — Prometheus exposition-format label-value escaping,
// shared by every native /metrics producer (the operator's status
// server, the metrics exporter) and pinned against the Python twin
// (tpu_cluster/telemetry.py `_escape`, tests/fake_apiserver.py
// `prom_escape`) by native/operator/selftest.cc + tests.
//
// The exposition format requires backslash, double-quote and newline to
// be escaped inside label VALUES; an unescaped dynamic value (a device
// path, a request path) would let hostile bytes forge extra samples or
// truncate the series identity.

#ifndef TPU_NATIVE_COMMON_PROMESCAPE_H_
#define TPU_NATIVE_COMMON_PROMESCAPE_H_

#include <string>

namespace promescape {

inline std::string EscapeLabelValue(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    switch (c) {
      case '\\': out += "\\\\"; break;
      case '"':  out += "\\\""; break;
      case '\n': out += "\\n"; break;
      default:   out += c;
    }
  }
  return out;
}

}  // namespace promescape

#endif  // TPU_NATIVE_COMMON_PROMESCAPE_H_
