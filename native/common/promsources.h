// promsources — shared discovery of runtime-metrics textfile sources.
//
// One implementation of "which writer files feed this read, in what
// order" for every consumer (tpu-metrics-exporter's relay, tpu-info's
// merge): the legacy single --metrics-file plus every *.prom in the
// metrics.d drop-dir, files stale past stale_after_s evicted, survivors
// ordered oldest-first by NANOSECOND mtime so a consumer applying them in
// order gives the newest writer the last word. Two binaries re-implementing
// this would drift on eviction/ordering rules and report different unions
// for the same node.

#ifndef TPU_NATIVE_COMMON_PROMSOURCES_H_
#define TPU_NATIVE_COMMON_PROMSOURCES_H_

#include <ctype.h>
#include <dirent.h>
#include <stdint.h>
#include <stdio.h>
#include <sys/stat.h>
#include <time.h>

#include <algorithm>
#include <string>
#include <vector>

namespace promsources {

struct Source {
  int64_t mtime_ns;
  std::string path;
  std::string stem;  // sanitized filename stem — the writer identity
};

// Writers name their own files on a shared hostPath; the stem becomes a
// Prometheus label VALUE, so it is restricted to label-safe characters —
// a quote/backslash/newline in a hostile filename must not break (or
// smuggle series into) the scrape text. When sanitization CHANGES the
// stem, a short hash of the raw bytes is appended so two distinct raw
// names cannot collapse onto one writer label ("train job" vs
// "train_job" impersonation — the cross-writer isolation the label
// exists for).
inline std::string SanitizeStem(const std::string& raw) {
  std::string out;
  bool changed = false;
  for (char c : raw) {
    bool ok = isalnum(static_cast<unsigned char>(c)) || c == '_' ||
              c == '-' || c == '.';
    out += ok ? c : '_';
    changed |= !ok;
  }
  // The hashed form "<stem>-<8 hex>" must be UNREACHABLE from clean
  // input: a clean filename that already ends in -xxxxxxxx could
  // otherwise be chosen byte-identical to another writer's hashed label
  // (impersonation through the front door). Force-hash that shape too.
  if (!changed && out.size() > 9 && out[out.size() - 9] == '-') {
    bool hexish = true;
    for (size_t i = out.size() - 8; i < out.size(); ++i)
      hexish &= isxdigit(static_cast<unsigned char>(out[i])) &&
                !isupper(static_cast<unsigned char>(out[i]));
    changed = hexish;
  }
  if (changed) {
    uint32_t h = 2166136261u;  // FNV-1a of the raw bytes
    for (char c : raw) {
      h ^= static_cast<unsigned char>(c);
      h *= 16777619u;
    }
    char buf[12];
    snprintf(buf, sizeof(buf), "-%08x", h);
    out += buf;
  }
  return out;
}

// A runaway writer (or an attack) dropping thousands of files must not
// blow up every scrape: the newest kMaxSources drop-dir files win (they
// carry the live values under newest-wins dedup) and only those are
// OPENED/READ; the overflow is reported via dropped_count. Note the
// residual cost: enumerating mtimes still stat()s every *.prom in the
// dir — the cap bounds reads, not directory enumeration.
constexpr size_t kMaxSources = 256;

// stale_count / dropped_count (nullable) receive eviction/overflow counts.
inline std::vector<Source> Collect(const std::string& file,
                                   const std::string& dir,
                                   int stale_after_s,
                                   int* stale_count,
                                   int* dropped_count = nullptr) {
  std::vector<Source> out;
  time_t now = time(nullptr);
  int stale = 0;
  auto consider = [&](const std::string& path, const std::string& stem,
                      bool sanitize) {
    struct stat sb;
    if (stat(path.c_str(), &sb) != 0 || !S_ISREG(sb.st_mode)) return;
    if (stale_after_s > 0 && now - sb.st_mtime > stale_after_s) {
      ++stale;
      return;
    }
    int64_t ns = static_cast<int64_t>(sb.st_mtim.tv_sec) * 1000000000 +
                 sb.st_mtim.tv_nsec;
    out.push_back({ns, path, sanitize ? SanitizeStem(stem) : stem});
  };
  if (!dir.empty()) {
    if (DIR* d = opendir(dir.c_str())) {
      struct dirent* ent;
      while ((ent = readdir(d)) != nullptr) {
        std::string name = ent->d_name;
        if (name.size() > 5 &&
            name.compare(name.size() - 5, 5, ".prom") == 0)
          consider(dir + "/" + name, name.substr(0, name.size() - 5),
                   true);
      }
      closedir(d);
    }
  }
  std::stable_sort(out.begin(), out.end(),
                   [](const Source& a, const Source& b) {
                     return a.mtime_ns < b.mtime_ns;
                   });
  int dropped = 0;
  if (out.size() > kMaxSources) {
    dropped = static_cast<int>(out.size() - kMaxSources);
    out.erase(out.begin(), out.end() - kMaxSources);  // keep newest
  }
  // The explicitly configured legacy file (empty stem = no writer label)
  // is EXEMPT from the cap: a drop-dir flood must not be able to evict
  // the operator-configured source's series from the scrape. Added after
  // the cap, re-sorted so newest-wins ordering still holds.
  if (!file.empty()) {
    size_t before = out.size();
    consider(file, "", false);
    if (out.size() > before)
      std::stable_sort(out.begin(), out.end(),
                       [](const Source& a, const Source& b) {
                         return a.mtime_ns < b.mtime_ns;
                       });
  }
  if (stale_count) *stale_count = stale;
  if (dropped_count) *dropped_count = dropped;
  return out;
}

}  // namespace promsources

#endif  // TPU_NATIVE_COMMON_PROMSOURCES_H_
