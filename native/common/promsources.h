// promsources — shared discovery of runtime-metrics textfile sources.
//
// One implementation of "which writer files feed this read, in what
// order" for every consumer (tpu-metrics-exporter's relay, tpu-info's
// merge): the legacy single --metrics-file plus every *.prom in the
// metrics.d drop-dir, files stale past stale_after_s evicted, survivors
// ordered oldest-first by NANOSECOND mtime so a consumer applying them in
// order gives the newest writer the last word. Two binaries re-implementing
// this would drift on eviction/ordering rules and report different unions
// for the same node.

#ifndef TPU_NATIVE_COMMON_PROMSOURCES_H_
#define TPU_NATIVE_COMMON_PROMSOURCES_H_

#include <dirent.h>
#include <sys/stat.h>
#include <time.h>

#include <algorithm>
#include <string>
#include <vector>

namespace promsources {

struct Source {
  int64_t mtime_ns;
  std::string path;
  std::string stem;  // filename without .prom — the writer identity
};

// stale_count (nullable) receives the number of evicted files.
inline std::vector<Source> Collect(const std::string& file,
                                   const std::string& dir,
                                   int stale_after_s,
                                   int* stale_count) {
  std::vector<Source> out;
  time_t now = time(nullptr);
  int stale = 0;
  auto consider = [&](const std::string& path, const std::string& stem) {
    struct stat sb;
    if (stat(path.c_str(), &sb) != 0 || !S_ISREG(sb.st_mode)) return;
    if (stale_after_s > 0 && now - sb.st_mtime > stale_after_s) {
      ++stale;
      return;
    }
    int64_t ns = static_cast<int64_t>(sb.st_mtim.tv_sec) * 1000000000 +
                 sb.st_mtim.tv_nsec;
    out.push_back({ns, path, stem});
  };
  // the legacy single file carries no writer identity (empty stem)
  if (!file.empty()) consider(file, "");
  if (!dir.empty()) {
    if (DIR* d = opendir(dir.c_str())) {
      struct dirent* ent;
      while ((ent = readdir(d)) != nullptr) {
        std::string name = ent->d_name;
        if (name.size() > 5 &&
            name.compare(name.size() - 5, 5, ".prom") == 0)
          consider(dir + "/" + name, name.substr(0, name.size() - 5));
      }
      closedir(d);
    }
  }
  std::stable_sort(out.begin(), out.end(),
                   [](const Source& a, const Source& b) {
                     return a.mtime_ns < b.mtime_ns;
                   });
  if (stale_count) *stale_count = stale;
  return out;
}

}  // namespace promsources

#endif  // TPU_NATIVE_COMMON_PROMSOURCES_H_
