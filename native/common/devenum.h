// Shared TPU device-node enumeration for the native daemons.
//
// All four daemons (tpud, tpu-info, tpu-metrics-exporter, tpu-tfd) discover
// chips from the host device tree the same way: glob a pattern
// (re-rootable under a fake tree for tests), parse the chip index from the
// basename, sort by index. One implementation here so the daemons cannot
// drift on which device nodes they count.
//
// Accepted basenames (matches the Python oracle rule in
// tpu_cluster/discovery/devices.py): the chip index is the trailing digit
// run, whatever the prefix — the glob names the device namespace:
//   accel0, accel_7  -> 0, 7
//   tpu3             -> 3 (custom --device-glob)
//   45               -> 45 (/dev/vfio/<group>)
//   vfio, README     -> rejected (no trailing digits)
#pragma once

#include <string>
#include <vector>

namespace devenum {

struct Node {
  int index;
  std::string path;
};

// Re-root an absolute glob pattern under `root` ("" = unchanged):
// Reroot("/dev/accel*", "/tmp/t") == "/tmp/t/dev/accel*".
std::string Reroot(const std::string& pattern, const std::string& root);

// -1 when the basename is not a device node name.
int ParseIndex(const std::string& basename);

// Glob + parse + sort by index.
std::vector<Node> Enumerate(const std::string& pattern,
                            const std::string& devfs_root);

}  // namespace devenum
