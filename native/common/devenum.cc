#include "devenum.h"

#include <glob.h>

#include <algorithm>
#include <cctype>
#include <cstdlib>

namespace devenum {

std::string Reroot(const std::string& pattern, const std::string& root) {
  if (root.empty()) return pattern;
  std::string rel = pattern;
  while (!rel.empty() && rel[0] == '/') rel.erase(0, 1);
  return root + "/" + rel;
}

int ParseIndex(const std::string& basename) {
  if (basename.empty() ||
      !isdigit(static_cast<unsigned char>(basename.back())))
    return -1;
  size_t digits = basename.size();
  while (digits > 0 &&
         isdigit(static_cast<unsigned char>(basename[digits - 1])))
    --digits;
  return atoi(basename.c_str() + digits);
}

std::vector<Node> Enumerate(const std::string& pattern,
                            const std::string& devfs_root) {
  std::vector<Node> out;
  glob_t g = {};
  if (glob(Reroot(pattern, devfs_root).c_str(), 0, nullptr, &g) == 0) {
    for (size_t i = 0; i < g.gl_pathc; ++i) {
      std::string path = g.gl_pathv[i];
      int idx = ParseIndex(path.substr(path.find_last_of('/') + 1));
      if (idx >= 0) out.push_back({idx, path});
    }
  }
  globfree(&g);
  std::sort(out.begin(), out.end(),
            [](const Node& a, const Node& b) { return a.index < b.index; });
  return out;
}

}  // namespace devenum
