// Shared bounded HTTP request-head reader for the single-threaded daemons
// (tpu-metrics-exporter, tpu-operator status server).
//
// Reads from fd until the end of the request head (\r\n\r\n), the buffer
// fills, EOF/error/RCVTIMEO, the wall-clock deadline passes (RCVTIMEO only
// bounds each read — a drip-feeding client must not hold the daemon for
// buffer-size reads), or *stop is raised. Returns the byte count read;
// buf is always NUL-terminated.
#pragma once

#include <signal.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <cstddef>

namespace httpread {

inline size_t ReadRequestHead(int fd, char* buf, size_t cap,
                              volatile sig_atomic_t* stop,
                              int deadline_s = 2) {
  size_t have = 0;
  buf[0] = 0;
  time_t deadline = time(nullptr) + deadline_s;
  while (have < cap - 1 && !(stop && *stop) && time(nullptr) <= deadline) {
    ssize_t n = read(fd, buf + have, cap - 1 - have);
    if (n <= 0) break;  // EOF, error, or RCVTIMEO
    have += static_cast<size_t>(n);
    buf[have] = 0;
    if (strstr(buf, "\r\n\r\n")) break;
  }
  return have;
}

}  // namespace httpread
