#include "workqueue.h"

#include <algorithm>

namespace workqueue {

RateLimitedQueue::RateLimitedQueue(size_t max_depth, int base_delay_ms,
                                   int max_delay_ms)
    : max_depth_(max_depth),
      base_delay_ms_(base_delay_ms < 1 ? 1 : base_delay_ms),
      max_delay_ms_(max_delay_ms < base_delay_ms_ ? base_delay_ms_
                                                  : max_delay_ms) {}

void RateLimitedQueue::AddLocked(const std::string& key) {
  if (shutting_down_) return;
  if (dirty_.count(key)) return;  // already queued or pending re-queue
  dirty_.insert(key);
  if (processing_.count(key)) return;  // re-queued by Done()
  if (max_depth_ > 0 && queue_.size() >= max_depth_) {
    // Shed the OLDEST key: it has waited longest, so it is the one the
    // next full resync is most likely to re-discover anyway. The flag
    // makes that resync an obligation, not a hope.
    const std::string oldest = queue_.front();
    queue_.pop_front();
    if (!processing_.count(oldest)) dirty_.erase(oldest);
    ++sheds_;
    resync_needed_ = true;
  }
  queue_.push_back(key);
  cv_.notify_one();
}

void RateLimitedQueue::PromoteDueLocked(Clock::time_point now) {
  while (!delayed_.empty() && delayed_.begin()->first <= now) {
    std::string key = delayed_.begin()->second;
    delayed_.erase(delayed_.begin());
    AddLocked(key);
  }
}

void RateLimitedQueue::Add(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  ++adds_;
  PromoteDueLocked(Clock::now());
  AddLocked(key);
}

void RateLimitedQueue::AddRateLimited(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  if (shutting_down_) return;
  ++adds_;
  ++retries_;
  int strikes = ++strikes_[key];
  long long delay = base_delay_ms_;
  for (int i = 1; i < strikes && delay < max_delay_ms_; ++i) delay *= 2;
  delay = std::min<long long>(delay, max_delay_ms_);
  delayed_.emplace(Clock::now() + std::chrono::milliseconds(delay), key);
  cv_.notify_one();
}

void RateLimitedQueue::AddAfter(const std::string& key, int delay_ms) {
  std::lock_guard<std::mutex> lock(mu_);
  if (shutting_down_) return;
  ++adds_;
  if (delay_ms <= 0) {
    PromoteDueLocked(Clock::now());
    AddLocked(key);
    return;
  }
  delayed_.emplace(Clock::now() + std::chrono::milliseconds(delay_ms), key);
  cv_.notify_one();
}

void RateLimitedQueue::Forget(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  strikes_.erase(key);
}

bool RateLimitedQueue::Get(std::string* key, int wait_ms) {
  std::unique_lock<std::mutex> lock(mu_);
  Clock::time_point deadline =
      Clock::now() + std::chrono::milliseconds(wait_ms < 0 ? 0 : wait_ms);
  for (;;) {
    PromoteDueLocked(Clock::now());
    if (!queue_.empty()) break;
    if (shutting_down_) return false;
    Clock::time_point now = Clock::now();
    if (now >= deadline) return false;
    // wake for whichever comes first: the wait deadline or the next
    // delayed key falling due
    Clock::time_point until = deadline;
    if (!delayed_.empty() && delayed_.begin()->first < until)
      until = delayed_.begin()->first;
    // Wait against a system_clock deadline: a steady_clock wait_until
    // lowers to pthread_cond_clockwait on this libstdc++, which older
    // libtsan builds do not intercept — TSan then believes the waiter
    // never released mu_ and reports phantom double-locks. The
    // timedwait path is intercepted; a wall-clock jump only perturbs
    // one wakeup, and the loop re-checks the steady deadline anyway.
    cv_.wait_until(lock,
                   std::chrono::system_clock::now() +
                       std::chrono::duration_cast<
                           std::chrono::system_clock::duration>(until - now));
  }
  *key = queue_.front();
  queue_.pop_front();
  dirty_.erase(*key);
  processing_.insert(*key);
  return true;
}

void RateLimitedQueue::Done(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  processing_.erase(key);
  if (dirty_.count(key)) {
    // Add() landed while this key was being processed: the event is
    // honored by re-queueing, never dropped (the blind-window fix).
    if (max_depth_ > 0 && queue_.size() >= max_depth_) {
      const std::string oldest = queue_.front();
      queue_.pop_front();
      if (!processing_.count(oldest)) dirty_.erase(oldest);
      ++sheds_;
      resync_needed_ = true;
    }
    queue_.push_back(key);
    cv_.notify_one();
  }
}

void RateLimitedQueue::ShutDown() {
  std::lock_guard<std::mutex> lock(mu_);
  shutting_down_ = true;
  cv_.notify_all();
}

bool RateLimitedQueue::shutting_down() const {
  std::lock_guard<std::mutex> lock(mu_);
  return shutting_down_;
}

int RateLimitedQueue::NextDelayMs() const {
  std::lock_guard<std::mutex> lock(mu_);
  if (!queue_.empty()) return 0;
  if (delayed_.empty()) return -1;
  auto due = delayed_.begin()->first;
  auto now = Clock::now();
  if (due <= now) return 0;
  return static_cast<int>(
      std::chrono::duration_cast<std::chrono::milliseconds>(due - now)
          .count()) +
      1;
}

long long RateLimitedQueue::adds() const {
  std::lock_guard<std::mutex> lock(mu_);
  return adds_;
}

long long RateLimitedQueue::retries() const {
  std::lock_guard<std::mutex> lock(mu_);
  return retries_;
}

size_t RateLimitedQueue::depth() const {
  std::lock_guard<std::mutex> lock(mu_);
  return queue_.size();
}

size_t RateLimitedQueue::sheds() const {
  std::lock_guard<std::mutex> lock(mu_);
  return sheds_;
}

bool RateLimitedQueue::TakeResyncNeeded() {
  std::lock_guard<std::mutex> lock(mu_);
  bool need = resync_needed_;
  resync_needed_ = false;
  return need;
}

int RateLimitedQueue::StrikesForTest(const std::string& key) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = strikes_.find(key);
  return it == strikes_.end() ? 0 : it->second;
}

}  // namespace workqueue
