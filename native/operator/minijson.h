// minijson — small self-contained JSON parser/serializer for the operator.
//
// The tpu-operator (gpu-operator analog, reference README.md:101-110) talks
// to the kube-apiserver in JSON: it POSTs manifest documents it read from the
// bundle dir and extracts a handful of status fields (DaemonSet
// desired/ready counts etc.) from responses. Full DOM, no streaming; inputs
// are trusted-size (manifests, single-object API responses).

#ifndef TPU_NATIVE_OPERATOR_MINIJSON_H_
#define TPU_NATIVE_OPERATOR_MINIJSON_H_

#include <map>
#include <memory>
#include <string>
#include <vector>

namespace minijson {

class Value;
using ValuePtr = std::shared_ptr<Value>;

class Value {
 public:
  enum class Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Value() : type_(Type::kNull) {}
  explicit Value(bool b) : type_(Type::kBool), bool_(b) {}
  explicit Value(double d) : type_(Type::kNumber), num_(d) {}
  explicit Value(const std::string& s) : type_(Type::kString), str_(s) {}

  static ValuePtr MakeObject();
  static ValuePtr MakeArray();

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::kNull; }
  bool is_object() const { return type_ == Type::kObject; }
  bool is_array() const { return type_ == Type::kArray; }
  bool is_string() const { return type_ == Type::kString; }
  bool is_number() const { return type_ == Type::kNumber; }
  bool is_bool() const { return type_ == Type::kBool; }

  bool as_bool(bool fallback = false) const {
    return type_ == Type::kBool ? bool_ : fallback;
  }
  double as_number(double fallback = 0) const {
    return type_ == Type::kNumber ? num_ : fallback;
  }
  const std::string& as_string() const { return str_; }

  // Object access. Get returns nullptr when absent or not an object.
  ValuePtr Get(const std::string& key) const;
  void Set(const std::string& key, ValuePtr v);
  const std::vector<std::pair<std::string, ValuePtr>>& items() const {
    return obj_;
  }

  // Array access.
  const std::vector<ValuePtr>& elements() const { return arr_; }
  void Append(ValuePtr v) { arr_.push_back(std::move(v)); }

  // Dotted-path convenience: Path("status.numberReady").
  ValuePtr Path(const std::string& dotted) const;
  std::string PathString(const std::string& dotted,
                         const std::string& fallback = "") const;
  double PathNumber(const std::string& dotted, double fallback = 0) const;

  std::string Dump() const;

 private:
  void DumpTo(std::string* out) const;

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::vector<ValuePtr> arr_;
  std::vector<std::pair<std::string, ValuePtr>> obj_;  // insertion order
};

// Returns nullptr on malformed input; *err gets a position-tagged message.
ValuePtr Parse(const std::string& text, std::string* err = nullptr);

}  // namespace minijson

#endif  // TPU_NATIVE_OPERATOR_MINIJSON_H_
