// kubeapi — Kubernetes REST path construction + readiness evaluation for the
// object kinds the TPU stack manages. Kept apart from the daemon loop so the
// selftest binary can pin this logic without a server.

#ifndef TPU_NATIVE_OPERATOR_KUBEAPI_H_
#define TPU_NATIVE_OPERATOR_KUBEAPI_H_

#include <time.h>

#include <string>
#include <utility>
#include <vector>

#include "minijson.h"

namespace kubeapi {

// "/api/v1/namespaces/tpu-system/daemonsets" style collection path for the
// object's (apiVersion, kind, metadata.namespace). Returns "" (and sets
// *err) for kinds outside the supported set.
std::string CollectionPath(const minijson::Value& obj, std::string* err);

// CollectionPath + "/<metadata.name>".
std::string ObjectPath(const minijson::Value& obj, std::string* err);

// Workload readiness from an object's status:
//   DaemonSet:  desiredNumberScheduled == numberReady (and observed spec)
//   Deployment: spec.replicas == status.readyReplicas
//   Job:        status.succeeded >= spec.completions (default 1)
//   other kinds: ready on creation
bool IsReady(const minijson::Value& obj);

// True for kinds with no namespace segment (Namespace, ClusterRole, ...).
bool IsClusterScoped(const std::string& kind);

// Collection paths of every kind the operator can manage (the Plurals
// table), for the stale-object prune sweep — derived from the same table
// as path construction so the two cannot drift. Excludes kinds a bundle
// never labels (Namespace, Event, Pod). Namespaced collections are
// omitted when ns is empty.
std::vector<std::string> SweepCollections(const std::string& ns);

// Kinds the operator treats as operand *workloads* — the kinds whose
// watch events are generation-filtered drift (operator_main.cc
// OnInformerEvent). This is the C++ half of a pinned twin table:
// the Python bundle linter's OPERAND_WORKLOAD_KINDS
// (tpu_cluster/lint.py) names the same GVKs, and native/operator/
// selftest.cc + tests/test_lint.py pin the two against each other (same
// pattern as kubeclient::RetryableStatus).
const std::vector<std::string>& OperandWorkloadKinds();

// The field manager this operator applies under (server-side apply,
// KEP-555): per-field ownership in metadata.managedFields is tracked per
// manager, and the operator's name is deliberately DISTINCT from the
// CLI's ("tpuctl", kubeapply.FIELD_MANAGER) so the two co-own the
// bundle's fields instead of force-reverting each other. The C++ half of
// a pinned twin table: kubeapply.OPERATOR_FIELD_MANAGER names the same
// string, pinned by selftest.cc and a Python source-grep in
// tests/test_apply.py (the RetryableStatus pattern).
const char* FieldManager();

// Prometheus metric families the operator's /metrics endpoint MUST
// emit (every configuration — conditional families like the
// --leader-elect-only tpu_operator_leader gauge are excluded). The C++
// half of a pinned twin table: tpu_cluster/telemetry.py
// OPERATOR_METRIC_NAMES names the same families, pinned by selftest.cc
// (compiler-side) and a Python source-grep in tests/test_telemetry.py
// (compiler-free), and `tpuctl verify --config operator-metrics` FAILs a
// live scrape missing any of them. Renaming a family here without its
// twin breaks the pin before it breaks a dashboard.
const std::vector<std::string>& OperatorMetricNames();

// Chrome trace-event slice names the operator's trace emitter uses
// (reconcile-pass / apply-object / ready-wait / watch-sleep /
// drift-event). The C++ half of a pinned twin table:
// tpu_cluster/telemetry.py OPERATOR_TRACE_EVENTS names the same slices,
// pinned by selftest.cc (compiler-side), a Python source-grep in
// tests/test_telemetry.py (compiler-free), and a CI grep over the
// operator's emitted trace artifact. Renaming a slice here without its
// twin breaks the pin before it breaks a merged timeline.
const std::vector<std::string>& OperatorTraceEventNames();

// The object annotation carrying an apply's W3C trace context
// ("tpu-stack.dev/traceparent"): tpuctl stamps it on objects it
// mutates, and the operator reads it off live objects to tag its
// reconcile slices with the originating trace id. Twin of
// tpu_cluster/telemetry.py TRACEPARENT_ANNOTATION (selftest +
// source-grep pinned, the FieldManager pattern).
const char* TraceparentAnnotation();

// (trace_id, parent_id) from a W3C traceparent header value; ("", "")
// for absent/malformed input. Twin of telemetry.parse_traceparent.
std::pair<std::string, std::string> ParseTraceparent(
    const std::string& header);

// Histogram bucket selection shared by every native histogram render:
// the index of the FIRST bound with value <= bound (cumulative `le`
// semantics — a value exactly equal to a bound lands IN that bucket,
// matching tpu_cluster.telemetry.Histogram.observe), or n for the
// implicit +Inf bucket. Pinned against the Python twin by selftest.cc
// and the bucket-boundary parity test in tests/test_telemetry.py.
size_t HistogramBucketIndex(double value, const double* bounds, size_t n);

// Minimal Chrome trace-event emitter — the kubeapi twin of
// tpu_cluster/telemetry.py's Tracer export schema: ph=X complete slices
// and ph=i instant marks with microsecond offsets from construction,
// dumped as the JSON-object form (`{"traceEvents": [...], "otherData":
// {"producer": "tpu-operator", "epoch": ...}}`) that `tpuctl trace
// merge` and Perfetto load directly. BOUNDED like the CLI's flight
// recorder: at most kMaxEvents events are retained (oldest dropped,
// drop count surfaced in otherData) so an operator running for months
// cannot grow an unbounded trace. Single-threaded by contract, like the
// daemon that owns it.
class TraceEmitter {
 public:
  static constexpr size_t kMaxEvents = 4096;

  TraceEmitter();

  // Microseconds since construction (slice timestamps).
  double NowUs() const;

  using Args = std::vector<std::pair<std::string, std::string>>;

  // One ph=X complete slice [ts_us, ts_us+dur_us).
  void AddComplete(const std::string& name, const std::string& cat,
                   double ts_us, double dur_us, const Args& args);

  // One ph=i instant mark at NowUs().
  void AddInstant(const std::string& name, const std::string& cat,
                  const Args& args);

  // The full Chrome trace JSON document (one line, trailing newline).
  std::string DumpChromeJson() const;

  size_t size() const { return events_.size(); }
  size_t dropped() const { return dropped_; }

 private:
  struct Event {
    bool instant;
    std::string name, cat;
    double ts_us, dur_us;
    Args args;
  };

  double epoch_;           // wall clock at t0_ (merge alignment anchor)
  struct timespec t0_;     // monotonic zero for every ts
  std::vector<Event> events_;
  size_t dropped_ = 0;
};

}  // namespace kubeapi

#endif  // TPU_NATIVE_OPERATOR_KUBEAPI_H_
