// kubeapi — Kubernetes REST path construction + readiness evaluation for the
// object kinds the TPU stack manages. Kept apart from the daemon loop so the
// selftest binary can pin this logic without a server.

#ifndef TPU_NATIVE_OPERATOR_KUBEAPI_H_
#define TPU_NATIVE_OPERATOR_KUBEAPI_H_

#include <string>
#include <vector>

#include "minijson.h"

namespace kubeapi {

// "/api/v1/namespaces/tpu-system/daemonsets" style collection path for the
// object's (apiVersion, kind, metadata.namespace). Returns "" (and sets
// *err) for kinds outside the supported set.
std::string CollectionPath(const minijson::Value& obj, std::string* err);

// CollectionPath + "/<metadata.name>".
std::string ObjectPath(const minijson::Value& obj, std::string* err);

// Workload readiness from an object's status:
//   DaemonSet:  desiredNumberScheduled == numberReady (and observed spec)
//   Deployment: spec.replicas == status.readyReplicas
//   Job:        status.succeeded >= spec.completions (default 1)
//   other kinds: ready on creation
bool IsReady(const minijson::Value& obj);

// True for kinds with no namespace segment (Namespace, ClusterRole, ...).
bool IsClusterScoped(const std::string& kind);

// Collection paths of every kind the operator can manage (the Plurals
// table), for the stale-object prune sweep — derived from the same table
// as path construction so the two cannot drift. Excludes kinds a bundle
// never labels (Namespace, Event, Pod). Namespaced collections are
// omitted when ns is empty.
std::vector<std::string> SweepCollections(const std::string& ns);

// Kinds the operator treats as operand *workloads* — the collections the
// drift watch holds open across the sleep (operator_main.cc
// OwnedWorkloadCollections). This is the C++ half of a pinned twin table:
// the Python bundle linter's OPERAND_WORKLOAD_KINDS
// (tpu_cluster/lint.py) names the same GVKs, and native/operator/
// selftest.cc + tests/test_lint.py pin the two against each other (same
// pattern as kubeclient::RetryableStatus).
const std::vector<std::string>& OperandWorkloadKinds();

// The field manager this operator applies under (server-side apply,
// KEP-555): per-field ownership in metadata.managedFields is tracked per
// manager, and the operator's name is deliberately DISTINCT from the
// CLI's ("tpuctl", kubeapply.FIELD_MANAGER) so the two co-own the
// bundle's fields instead of force-reverting each other. The C++ half of
// a pinned twin table: kubeapply.OPERATOR_FIELD_MANAGER names the same
// string, pinned by selftest.cc and a Python source-grep in
// tests/test_apply.py (the RetryableStatus pattern).
const char* FieldManager();

// Prometheus metric families the operator's /metrics endpoint MUST
// emit (every configuration — conditional families like the
// --leader-elect-only tpu_operator_leader gauge are excluded). The C++
// half of a pinned twin table: tpu_cluster/telemetry.py
// OPERATOR_METRIC_NAMES names the same families, pinned by selftest.cc
// (compiler-side) and a Python source-grep in tests/test_telemetry.py
// (compiler-free), and `tpuctl verify --config operator-metrics` FAILs a
// live scrape missing any of them. Renaming a family here without its
// twin breaks the pin before it breaks a dashboard.
const std::vector<std::string>& OperatorMetricNames();

}  // namespace kubeapi

#endif  // TPU_NATIVE_OPERATOR_KUBEAPI_H_
