#include "kubeapi.h"

#include <time.h>

#include <map>
#include <memory>

namespace kubeapi {

namespace {

// kind -> plural for every kind the operator bundle can contain. A lookup
// table beats naive pluralisation: it turns an unsupported kind into a loud
// error instead of a 404 against a misspelled path.
const std::map<std::string, std::string>& Plurals() {
  static const auto* m = new std::map<std::string, std::string>{
      {"Namespace", "namespaces"},
      {"ConfigMap", "configmaps"},
      {"Secret", "secrets"},
      {"Service", "services"},
      {"ServiceAccount", "serviceaccounts"},
      {"Pod", "pods"},
      {"DaemonSet", "daemonsets"},
      {"Event", "events"},
      {"Deployment", "deployments"},
      {"StatefulSet", "statefulsets"},
      {"Job", "jobs"},
      {"ClusterRole", "clusterroles"},
      {"ClusterRoleBinding", "clusterrolebindings"},
      {"Role", "roles"},
      {"RoleBinding", "rolebindings"},
  };
  return *m;
}

// kind -> apiVersion for every kind in Plurals(). A kind present in one
// table but not the other is a maintenance bug, caught by the selftest.
const std::map<std::string, std::string>& ApiVersions() {
  static const auto* m = new std::map<std::string, std::string>{
      {"Namespace", "v1"},
      {"ConfigMap", "v1"},
      {"Secret", "v1"},
      {"Service", "v1"},
      {"ServiceAccount", "v1"},
      {"Pod", "v1"},
      {"Event", "v1"},
      {"DaemonSet", "apps/v1"},
      {"Deployment", "apps/v1"},
      {"StatefulSet", "apps/v1"},
      {"Job", "batch/v1"},
      {"ClusterRole", "rbac.authorization.k8s.io/v1"},
      {"ClusterRoleBinding", "rbac.authorization.k8s.io/v1"},
      {"Role", "rbac.authorization.k8s.io/v1"},
      {"RoleBinding", "rbac.authorization.k8s.io/v1"},
  };
  return *m;
}

}  // namespace

bool IsClusterScoped(const std::string& kind) {
  return kind == "Namespace" || kind == "ClusterRole" ||
         kind == "ClusterRoleBinding" || kind == "Node" ||
         kind == "PersistentVolume";
}

std::string CollectionPath(const minijson::Value& obj, std::string* err) {
  std::string api_version = obj.PathString("apiVersion");
  std::string kind = obj.PathString("kind");
  auto it = Plurals().find(kind);
  if (api_version.empty() || it == Plurals().end()) {
    *err = "unsupported object: apiVersion='" + api_version + "' kind='" +
           kind + "'";
    return "";
  }
  // core group ("v1") lives under /api, named groups under /apis
  std::string prefix = api_version.find('/') == std::string::npos
                           ? "/api/" + api_version
                           : "/apis/" + api_version;
  if (IsClusterScoped(kind)) return prefix + "/" + it->second;
  std::string ns = obj.PathString("metadata.namespace", "default");
  return prefix + "/namespaces/" + ns + "/" + it->second;
}

std::string ObjectPath(const minijson::Value& obj, std::string* err) {
  std::string coll = CollectionPath(obj, err);
  if (coll.empty()) return "";
  std::string name = obj.PathString("metadata.name");
  if (name.empty()) {
    *err = "object has no metadata.name";
    return "";
  }
  return coll + "/" + name;
}

std::vector<std::string> SweepCollections(const std::string& ns) {
  std::vector<std::string> out;
  for (const auto& kv : Plurals()) {
    const std::string& kind = kv.first;
    // kinds a bundle never carries the operand label on: the Namespace
    // itself, Events, and Pods (labels sit on controllers, not their pods)
    if (kind == "Namespace" || kind == "Event" || kind == "Pod") continue;
    auto av = ApiVersions().find(kind);
    if (av == ApiVersions().end()) continue;  // selftest pins full coverage
    std::string prefix = av->second.find('/') == std::string::npos
                             ? "/api/" + av->second
                             : "/apis/" + av->second;
    if (IsClusterScoped(kind)) {
      out.push_back(prefix + "/" + kv.second);
    } else if (!ns.empty()) {
      out.push_back(prefix + "/namespaces/" + ns + "/" + kv.second);
    }
  }
  return out;
}

const char* FieldManager() {
  // Twin of tpu_cluster/kubeapply.py OPERATOR_FIELD_MANAGER (grep-pinned
  // by tests/test_apply.py; checked against selftest.cc). Changing it
  // orphans every field the deployed fleet's operators own — the old
  // manager's entries linger in managedFields until force-reapplied.
  return "tpu-operator";
}

const std::vector<std::string>& OperatorMetricNames() {
  // Twin table of tpu_cluster/telemetry.py OPERATOR_METRIC_NAMES (the
  // RetryableStatus pattern: selftest.cc pins this side, a Python
  // source-grep in tests/test_telemetry.py pins the equality, and the
  // live scrape is gated by `tpuctl verify --config operator-metrics`).
  // operator_main.cc's Metrics() must emit every family named here.
  static const auto* names = new std::vector<std::string>{
      "tpu_operator_objects",
      "tpu_operator_passes_total",
      "tpu_operator_healthy",
      "tpu_operator_consecutive_failures",
      "tpu_operator_policy_generation",
      "tpu_operator_reconcile_duration_seconds",
      "tpu_operator_watch_reconnects_total",
      "tpu_operator_queue_depth",
      "tpu_operator_sync_lag_seconds",
      "tpu_operator_workqueue_adds_total",
      "tpu_operator_workqueue_retries_total",
      "tpu_operator_workqueue_depth",
  };
  return *names;
}

const std::vector<std::string>& OperatorTraceEventNames() {
  // Twin table of tpu_cluster/telemetry.py OPERATOR_TRACE_EVENTS (the
  // OperatorMetricNames pattern: selftest.cc pins this side, a Python
  // source-grep in tests/test_telemetry.py pins the equality, and CI
  // greps the operator's emitted trace artifact for every name).
  // operator_main.cc's trace emitter must use exactly these slice names.
  static const auto* names = new std::vector<std::string>{
      "reconcile-pass",
      "apply-object",
      "ready-wait",
      "watch-sleep",
      "drift-event",
      "reconcile-object",
  };
  return *names;
}

const char* TraceparentAnnotation() {
  // Twin of tpu_cluster/telemetry.py TRACEPARENT_ANNOTATION (grep-pinned
  // by tests; checked against selftest.cc). tpuctl stamps it on objects
  // it mutates; renaming it here orphans the correlation the merged
  // timeline exists for.
  return "tpu-stack.dev/traceparent";
}

std::pair<std::string, std::string> ParseTraceparent(
    const std::string& header) {
  // 00-<32 hex>-<16 hex>-<2 hex>; anything malformed (or the reserved
  // all-zero ids) parses to ("", "") — a server/operator must tolerate
  // garbage headers and annotations.
  auto fail = std::make_pair(std::string(), std::string());
  size_t d1 = header.find('-');
  if (d1 == std::string::npos) return fail;
  size_t d2 = header.find('-', d1 + 1);
  if (d2 == std::string::npos) return fail;
  size_t d3 = header.find('-', d2 + 1);
  if (d3 == std::string::npos) return fail;
  if (header.find('-', d3 + 1) != std::string::npos) return fail;
  std::string trace_id = header.substr(d1 + 1, d2 - d1 - 1);
  std::string parent_id = header.substr(d2 + 1, d3 - d2 - 1);
  if (trace_id.size() != 32 || parent_id.size() != 16) return fail;
  bool trace_zero = true, parent_zero = true;
  for (char c : trace_id) {
    bool hex = (c >= '0' && c <= '9') || (c >= 'a' && c <= 'f') ||
               (c >= 'A' && c <= 'F');
    if (!hex) return fail;
    if (c != '0') trace_zero = false;
  }
  for (char c : parent_id) {
    bool hex = (c >= '0' && c <= '9') || (c >= 'a' && c <= 'f') ||
               (c >= 'A' && c <= 'F');
    if (!hex) return fail;
    if (c != '0') parent_zero = false;
  }
  if (trace_zero || parent_zero) return fail;
  return {trace_id, parent_id};
}

size_t HistogramBucketIndex(double value, const double* bounds, size_t n) {
  // Cumulative `le` semantics, the Python twin's exact comparison
  // (telemetry.Histogram.observe: `if v <= bound`): a value EQUAL to a
  // bound lands in that bucket, so two processes observing the same
  // boundary value render identical bucket lines.
  for (size_t i = 0; i < n; ++i)
    if (value <= bounds[i]) return i;
  return n;  // +Inf
}

TraceEmitter::TraceEmitter() {
  epoch_ = static_cast<double>(time(nullptr));
  clock_gettime(CLOCK_MONOTONIC, &t0_);
}

double TraceEmitter::NowUs() const {
  struct timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  return (now.tv_sec - t0_.tv_sec) * 1e6 + (now.tv_nsec - t0_.tv_nsec) / 1e3;
}

void TraceEmitter::AddComplete(const std::string& name,
                               const std::string& cat, double ts_us,
                               double dur_us, const Args& args) {
  if (events_.size() >= kMaxEvents) {
    // bounded ring: drop the oldest quarter in one move (amortized —
    // erasing one front element per insert would be quadratic)
    size_t drop = kMaxEvents / 4;
    events_.erase(events_.begin(), events_.begin() + drop);
    dropped_ += drop;
  }
  events_.push_back(Event{false, name, cat, ts_us < 0 ? 0 : ts_us,
                          dur_us < 0 ? 0 : dur_us, args});
}

void TraceEmitter::AddInstant(const std::string& name,
                              const std::string& cat, const Args& args) {
  if (events_.size() >= kMaxEvents) {
    size_t drop = kMaxEvents / 4;
    events_.erase(events_.begin(), events_.begin() + drop);
    dropped_ += drop;
  }
  events_.push_back(Event{true, name, cat, NowUs(), 0, args});
}

std::string TraceEmitter::DumpChromeJson() const {
  using minijson::Value;
  auto arr = Value::MakeArray();
  for (const auto& e : events_) {
    auto ev = Value::MakeObject();
    ev->Set("name", std::make_shared<Value>(e.name));
    ev->Set("cat", std::make_shared<Value>(e.cat));
    ev->Set("ph", std::make_shared<Value>(
        std::string(e.instant ? "i" : "X")));
    ev->Set("ts", std::make_shared<Value>(e.ts_us));
    if (e.instant) {
      ev->Set("s", std::make_shared<Value>(std::string("t")));
    } else {
      ev->Set("dur", std::make_shared<Value>(e.dur_us));
    }
    ev->Set("pid", std::make_shared<Value>(1.0));
    ev->Set("tid", std::make_shared<Value>(1.0));
    auto args = Value::MakeObject();
    for (const auto& kv : e.args)
      args->Set(kv.first, std::make_shared<Value>(kv.second));
    ev->Set("args", args);
    arr->Append(ev);
  }
  auto root = Value::MakeObject();
  root->Set("traceEvents", arr);
  root->Set("displayTimeUnit",
            std::make_shared<Value>(std::string("ms")));
  auto other = Value::MakeObject();
  other->Set("producer",
             std::make_shared<Value>(std::string("tpu-operator")));
  other->Set("epoch", std::make_shared<Value>(epoch_));
  other->Set("dropped_events",
             std::make_shared<Value>(static_cast<double>(dropped_)));
  root->Set("otherData", other);
  return root->Dump() + "\n";
}

const std::vector<std::string>& OperandWorkloadKinds() {
  // Twin table of tpu_cluster/lint.py OPERAND_WORKLOAD_KINDS (both are
  // apps/v1 kinds; CollectionPath supplies the group). A kind added here
  // without its Python twin (or vice versa) fails the selftest/test_lint
  // pins before it can ship skew between the linter's security-audit
  // boundary and the operator's drift-watch set.
  static const auto* kinds =
      new std::vector<std::string>{"DaemonSet", "Deployment"};
  return *kinds;
}

bool IsReady(const minijson::Value& obj) {
  std::string kind = obj.PathString("kind");
  // Upgrade semantics (kubectl `rollout status` parity, mirrored in
  // kubeapply.is_ready): when the object carries metadata.generation, a
  // status from an older generation must not satisfy the gate — on a
  // re-reconcile that PATCHes an existing DaemonSet/Deployment the old pods
  // are still Ready, so without the observedGeneration and updated-count
  // checks the stage gate would pass before the new pods roll. Objects
  // without generation tracking keep the plain count rules.
  double generation = obj.PathNumber("metadata.generation", -1);
  bool tracked = generation >= 0;
  if (tracked && (kind == "DaemonSet" || kind == "Deployment") &&
      obj.PathNumber("status.observedGeneration", 0) < generation) {
    return false;
  }
  if (kind == "DaemonSet") {
    double desired = obj.PathNumber("status.desiredNumberScheduled", -1);
    double ready = obj.PathNumber("status.numberReady", -2);
    if (tracked &&
        obj.PathNumber("status.updatedNumberScheduled", 0) < desired) {
      return false;
    }
    // A DaemonSet with nothing scheduled yet (desired 0 or missing status)
    // is NOT ready: on a real cluster desired becomes >0 once nodes match;
    // treating 0==0 as ready would open the gate before pods even exist.
    // Exception: clusters genuinely without matching nodes would wedge the
    // rollout; operators handle that case with --allow-empty-daemonsets.
    return desired >= 0 && desired == ready && desired > 0;
  }
  if (kind == "Deployment") {
    double want = obj.PathNumber("spec.replicas", 1);
    if (tracked && obj.PathNumber("status.updatedReplicas", 0) < want) {
      return false;
    }
    // Missing readyReplicas means zero ready pods — which satisfies a
    // deliberately scaled-to-zero Deployment (replicas: 0) immediately.
    double ready = obj.PathNumber("status.readyReplicas", 0);
    return ready >= want;
  }
  if (kind == "Job") {
    double want = obj.PathNumber("spec.completions", 1);
    return obj.PathNumber("status.succeeded", 0) >= want;
  }
  return true;  // config-ish kinds are ready by existing
}

}  // namespace kubeapi
