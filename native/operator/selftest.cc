// operator_selftest — unit checks for minijson + kubeapi + the watch
// reconnect backoff (no server needed).

#include <stdio.h>
#include <string.h>

#include "../common/promescape.h"
#include "informer.h"
#include "kubeapi.h"
#include "kubeclient.h"
#include "minijson.h"
#include "workqueue.h"

static int g_failures = 0;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                    \
    }                                                                  \
  } while (0)

static void TestJsonRoundtrip() {
  const char* doc =
      "{\"a\": [1, 2.5, -3], \"b\": {\"c\": \"x\\ny\", \"d\": true},"
      " \"e\": null, \"uni\": \"\\u00e9\\u0041\"}";
  std::string err;
  auto v = minijson::Parse(doc, &err);
  CHECK(v && err.empty());
  CHECK(v->Path("b.c")->as_string() == "x\ny");
  CHECK(v->Path("b.d")->as_bool());
  CHECK(v->Path("e")->is_null());
  CHECK(v->Get("a")->elements().size() == 3);
  CHECK(v->Get("a")->elements()[1]->as_number() == 2.5);
  CHECK(v->Get("uni")->as_string() == "\xc3\xa9" "A");
  // dump -> reparse -> identical dump (canonical form fixpoint)
  std::string d1 = v->Dump();
  auto v2 = minijson::Parse(d1, &err);
  CHECK(v2 && v2->Dump() == d1);
  // integers stay integers through the double representation
  auto n = minijson::Parse("{\"x\": 123456789012}");
  CHECK(n->Dump() == "{\"x\":123456789012}");
}

static void TestJsonErrors() {
  std::string err;
  CHECK(!minijson::Parse("{", &err) && !err.empty());
  CHECK(!minijson::Parse("{\"a\": }", &err));
  CHECK(!minijson::Parse("[1, 2] trailing", &err));
  CHECK(!minijson::Parse("\"unterminated", &err));
  CHECK(!minijson::Parse("01x", &err));
  // strict number grammar: strtod-isms are malformed JSON
  CHECK(!minijson::Parse("inf", &err));
  CHECK(!minijson::Parse("{\"x\": nan}", &err));
  CHECK(!minijson::Parse("0x10", &err));
  CHECK(!minijson::Parse("01", &err));
  CHECK(!minijson::Parse("1.", &err));
  CHECK(!minijson::Parse("1e", &err));
  CHECK(!minijson::Parse("-", &err));
  CHECK(minijson::Parse("-0.5e-3", &err) != nullptr);
}

static minijson::ValuePtr Obj(const char* text) {
  std::string err;
  auto v = minijson::Parse(text, &err);
  if (!v) fprintf(stderr, "bad test object: %s\n", err.c_str());
  return v;
}

static void TestPaths() {
  std::string err;
  auto ds = Obj(
      "{\"apiVersion\": \"apps/v1\", \"kind\": \"DaemonSet\","
      " \"metadata\": {\"name\": \"tpud\", \"namespace\": \"tpu-system\"}}");
  CHECK(kubeapi::CollectionPath(*ds, &err) ==
        "/apis/apps/v1/namespaces/tpu-system/daemonsets");
  CHECK(kubeapi::ObjectPath(*ds, &err) ==
        "/apis/apps/v1/namespaces/tpu-system/daemonsets/tpud");

  auto ns = Obj(
      "{\"apiVersion\": \"v1\", \"kind\": \"Namespace\","
      " \"metadata\": {\"name\": \"tpu-system\"}}");
  CHECK(kubeapi::ObjectPath(*ns, &err) == "/api/v1/namespaces/tpu-system");

  auto svc = Obj(
      "{\"apiVersion\": \"v1\", \"kind\": \"Service\","
      " \"metadata\": {\"name\": \"m\", \"namespace\": \"x\"}}");
  CHECK(kubeapi::CollectionPath(*svc, &err) ==
        "/api/v1/namespaces/x/services");

  auto crb = Obj(
      "{\"apiVersion\": \"rbac.authorization.k8s.io/v1\","
      " \"kind\": \"ClusterRoleBinding\", \"metadata\": {\"name\": \"b\"}}");
  CHECK(kubeapi::ObjectPath(*crb, &err) ==
        "/apis/rbac.authorization.k8s.io/v1/clusterrolebindings/b");

  auto bogus = Obj("{\"apiVersion\": \"v1\", \"kind\": \"Wombat\","
                   " \"metadata\": {\"name\": \"w\"}}");
  CHECK(kubeapi::CollectionPath(*bogus, &err).empty() && !err.empty());
}

static void TestSweepCollections() {
  // Every managed kind (Plurals) except the never-labeled three must be
  // swept — a kind added to one table but not the other is the drift this
  // pin exists to catch. Count: 15 kinds - Namespace/Event/Pod = 12.
  auto colls = kubeapi::SweepCollections("tpu-system");
  CHECK(colls.size() == 12);
  auto has = [&](const char* want) {
    for (const auto& c : colls)
      if (c == want) return true;
    return false;
  };
  CHECK(has("/apis/apps/v1/namespaces/tpu-system/daemonsets"));
  CHECK(has("/apis/apps/v1/namespaces/tpu-system/statefulsets"));
  CHECK(has("/api/v1/namespaces/tpu-system/secrets"));
  CHECK(has("/apis/batch/v1/namespaces/tpu-system/jobs"));
  CHECK(has("/apis/rbac.authorization.k8s.io/v1/clusterroles"));
  CHECK(has("/apis/rbac.authorization.k8s.io/v1/namespaces/tpu-system/"
            "roles"));
  // empty namespace: only the cluster-scoped collections remain
  CHECK(kubeapi::SweepCollections("").size() == 2);
}

static void TestReadiness() {
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"status\": {}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\","
      " \"status\": {\"desiredNumberScheduled\": 2, \"numberReady\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\","
      " \"status\": {\"desiredNumberScheduled\": 2, \"numberReady\": 2}}")));
  // desired==0: not ready by default (no nodes matched yet)
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\","
      " \"status\": {\"desiredNumberScheduled\": 0, \"numberReady\": 0}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"spec\": {\"replicas\": 2},"
      " \"status\": {\"readyReplicas\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"spec\": {\"replicas\": 2},"
      " \"status\": {\"readyReplicas\": 2}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"Job\", \"status\": {\"succeeded\": 1}}")));
  CHECK(!kubeapi::IsReady(*Obj("{\"kind\": \"Job\", \"status\": {}}")));
  CHECK(kubeapi::IsReady(*Obj("{\"kind\": \"ConfigMap\"}")));

  // Upgrade semantics (kubectl rollout status parity): with generation
  // tracking, old-generation status or lagging updated counts gate readiness
  // even while the previous pods are still Ready.
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"metadata\": {\"generation\": 2},"
      " \"status\": {\"observedGeneration\": 1,"
      " \"desiredNumberScheduled\": 2, \"numberReady\": 2,"
      " \"updatedNumberScheduled\": 2}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"metadata\": {\"generation\": 2},"
      " \"status\": {\"observedGeneration\": 2,"
      " \"desiredNumberScheduled\": 2, \"numberReady\": 2,"
      " \"updatedNumberScheduled\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"metadata\": {\"generation\": 2},"
      " \"status\": {\"observedGeneration\": 2,"
      " \"desiredNumberScheduled\": 2, \"numberReady\": 2,"
      " \"updatedNumberScheduled\": 2}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"metadata\": {\"generation\": 3},"
      " \"spec\": {\"replicas\": 2},"
      " \"status\": {\"observedGeneration\": 2, \"readyReplicas\": 2,"
      " \"updatedReplicas\": 2}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"metadata\": {\"generation\": 3},"
      " \"spec\": {\"replicas\": 2},"
      " \"status\": {\"observedGeneration\": 3, \"readyReplicas\": 2,"
      " \"updatedReplicas\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"metadata\": {\"generation\": 3},"
      " \"spec\": {\"replicas\": 2},"
      " \"status\": {\"observedGeneration\": 3, \"readyReplicas\": 2,"
      " \"updatedReplicas\": 2}}")));
}

static void TestRetryClassification() {
  // The shared failure taxonomy (C++ twin of tpu_cluster.kubeapply's
  // RetryPolicy — the two tables must never drift): transport status 0
  // and 429/5xx-gateway statuses retry; everything else is success or
  // terminal. 409 Conflict is deliberately NOT retryable — the apply path
  // resolves it semantically (re-GET then re-PATCH).
  const int retryable[] = {0, 429, 500, 502, 503, 504};
  for (int s : retryable) CHECK(kubeclient::RetryableStatus(s));
  const int not_retryable[] = {200, 201, 202, 301, 400, 401, 403,
                               404,  409, 410, 422, 501};
  for (int s : not_retryable) CHECK(!kubeclient::RetryableStatus(s));

  // Retry-After parsing (plain-http transport, lowercased header block):
  // seconds — integer or fractional — to ms; absent, the http-date form,
  // or garbage parse to 0 (caller falls back to computed backoff); a
  // hostile/buggy value clamps to an hour.
  CHECK(kubeclient::ParseRetryAfterMs(
            "content-type: application/json\r\nretry-after: 2") == 2000);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after:0.25") == 250);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after:  7\r\nx: y") == 7000);
  CHECK(kubeclient::ParseRetryAfterMs("content-type: text/plain") == 0);
  CHECK(kubeclient::ParseRetryAfterMs(
            "retry-after: wed, 21 oct 2026 07:28:00 gmt") == 0);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after: -5") == 0);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after: 999999") == 3600000);
}

static void TestOperandWorkloadTwinTable() {
  // Pinned twin table (same pattern as TestRetryClassification): the
  // kinds the operator drift-watches as operand workloads must be
  // exactly the GVKs the Python bundle linter treats as operand
  // workloads — tpu_cluster/lint.py OPERAND_WORKLOAD_KINDS pins
  // (apps/v1, DaemonSet) and (apps/v1, Deployment); tests/test_lint.py
  // greps THIS table out of kubeapi.cc to close the loop without a
  // compiler.
  const auto& kinds = kubeapi::OperandWorkloadKinds();
  CHECK(kinds.size() == 2);
  auto has = [&](const char* want) {
    for (const auto& k : kinds)
      if (k == want) return true;
    return false;
  };
  CHECK(has("DaemonSet"));
  CHECK(has("Deployment"));
  // the apiVersion half of the GVK twin: both kinds resolve to apps/v1
  // collections through the same Plurals/ApiVersions tables the operator
  // applies with
  for (const auto& k : kinds) {
    std::string err;
    auto obj = Obj(("{\"apiVersion\": \"apps/v1\", \"kind\": \"" + k +
                    "\", \"metadata\": {\"name\": \"x\", \"namespace\": "
                    "\"ns\"}}")
                       .c_str());
    std::string coll = kubeapi::CollectionPath(*obj, &err);
    CHECK(coll.rfind("/apis/apps/v1/", 0) == 0);
  }
}

static void TestFieldManagerTwin() {
  // The field-manager twin table (RetryableStatus pattern): the name the
  // operator applies under is pinned here and grep-pinned from Python
  // (tests/test_apply.py checks kubeapi.cc's initializer equals
  // kubeapply.OPERATOR_FIELD_MANAGER, and that it differs from the
  // CLI's "tpuctl"). Per-field ownership means a silent rename orphans
  // every field the deployed fleet's operators own.
  CHECK(strcmp(kubeapi::FieldManager(), "tpu-operator") == 0);
  CHECK(strcmp(kubeapi::FieldManager(), "tpuctl") != 0);
}

static void TestOperatorMetricNamesTwinTable() {
  // Pinned twin table (RetryableStatus pattern): the families the
  // operator's /metrics endpoint must emit — tpu_cluster/telemetry.py
  // OPERATOR_METRIC_NAMES names the same set, tests/test_telemetry.py
  // greps THIS table out of kubeapi.cc to close the loop without a
  // compiler, and `tpuctl verify --config operator-metrics` gates the
  // live scrape. A rename lands here before it lands on a dashboard.
  const auto& names = kubeapi::OperatorMetricNames();
  CHECK(names.size() == 12);
  auto has = [&](const char* want) {
    for (const auto& n : names)
      if (n == want) return true;
    return false;
  };
  CHECK(has("tpu_operator_objects"));
  CHECK(has("tpu_operator_passes_total"));
  CHECK(has("tpu_operator_healthy"));
  CHECK(has("tpu_operator_consecutive_failures"));
  CHECK(has("tpu_operator_policy_generation"));
  CHECK(has("tpu_operator_reconcile_duration_seconds"));
  CHECK(has("tpu_operator_watch_reconnects_total"));
  CHECK(has("tpu_operator_queue_depth"));
  CHECK(has("tpu_operator_sync_lag_seconds"));
  CHECK(has("tpu_operator_workqueue_adds_total"));
  CHECK(has("tpu_operator_workqueue_retries_total"));
  CHECK(has("tpu_operator_workqueue_depth"));
  // uniqueness + the namespace prefix every family must carry
  for (size_t i = 0; i < names.size(); ++i) {
    CHECK(names[i].rfind("tpu_operator_", 0) == 0);
    for (size_t j = i + 1; j < names.size(); ++j)
      CHECK(names[i] != names[j]);
  }
}

static void TestOperatorTraceEventNamesTwinTable() {
  // Pinned twin table (OperatorMetricNames pattern): the Chrome
  // trace-event slice names the operator's emitter uses —
  // tpu_cluster/telemetry.py OPERATOR_TRACE_EVENTS names the same set,
  // tests/test_telemetry.py greps THIS table out of kubeapi.cc, and CI
  // greps the emitted trace artifact. A rename lands here before it
  // lands on a broken merged timeline.
  const auto& names = kubeapi::OperatorTraceEventNames();
  CHECK(names.size() == 6);
  auto has = [&](const char* want) {
    for (const auto& n : names)
      if (n == want) return true;
    return false;
  };
  CHECK(has("reconcile-pass"));
  CHECK(has("apply-object"));
  CHECK(has("ready-wait"));
  CHECK(has("watch-sleep"));
  CHECK(has("drift-event"));
  CHECK(has("reconcile-object"));
  for (size_t i = 0; i < names.size(); ++i)
    for (size_t j = i + 1; j < names.size(); ++j)
      CHECK(names[i] != names[j]);
  // every pinned name must appear in operator_main.cc's emitter calls —
  // the Python grep re-checks this compiler-free
}

static void TestTraceparentTwinsAndParsing() {
  // The annotation name twin (FieldManager pattern): tpuctl stamps it,
  // the operator reads it — kubeapply/telemetry pin the same string.
  CHECK(strcmp(kubeapi::TraceparentAnnotation(),
               "tpu-stack.dev/traceparent") == 0);
  // W3C traceparent parsing: twin of telemetry.parse_traceparent.
  auto ok = kubeapi::ParseTraceparent(
      "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01");
  CHECK(ok.first == "0af7651916cd43dd8448eb211c80319c");
  CHECK(ok.second == "b7ad6b7169203331");
  CHECK(kubeapi::ParseTraceparent("").first.empty());
  CHECK(kubeapi::ParseTraceparent("garbage").first.empty());
  CHECK(kubeapi::ParseTraceparent("00-short-b7ad6b7169203331-01")
            .first.empty());
  CHECK(kubeapi::ParseTraceparent(  // reserved all-zero trace id
            "00-00000000000000000000000000000000-b7ad6b7169203331-01")
            .first.empty());
  CHECK(kubeapi::ParseTraceparent(  // non-hex bytes
            "00-0af7651916cd43dd8448eb211c80319z-b7ad6b7169203331-01")
            .first.empty());
  CHECK(kubeapi::ParseTraceparent(  // trailing extra segment
            "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01-xx")
            .first.empty());
}

static void TestHistogramBucketBoundary() {
  // Bucket-boundary parity pin (the ISSUE 8 satellite): a value EXACTLY
  // equal to a `le` bound lands IN that bucket — the same `v <= bound`
  // comparison telemetry.Histogram.observe uses, so Python and C++
  // renders of the same observations are bucket-for-bucket identical.
  const double bounds[] = {0.01, 0.1, 1.0};
  CHECK(kubeapi::HistogramBucketIndex(0.005, bounds, 3) == 0);
  CHECK(kubeapi::HistogramBucketIndex(0.01, bounds, 3) == 0);   // == le
  CHECK(kubeapi::HistogramBucketIndex(0.0100001, bounds, 3) == 1);
  CHECK(kubeapi::HistogramBucketIndex(0.1, bounds, 3) == 1);    // == le
  CHECK(kubeapi::HistogramBucketIndex(1.0, bounds, 3) == 2);    // == le
  CHECK(kubeapi::HistogramBucketIndex(1.5, bounds, 3) == 3);    // +Inf
  CHECK(kubeapi::HistogramBucketIndex(-1.0, bounds, 3) == 0);
}

static void TestPromEscapeLabelValue() {
  // Seeded-hostile-label pin (exposition-format escaping; the
  // MetricsRegistry.render twin): backslash, double quote and newline
  // must escape, everything else passes through byte-identical.
  CHECK(promescape::EscapeLabelValue("plain-value_1") == "plain-value_1");
  CHECK(promescape::EscapeLabelValue("say \"hi\"") == "say \\\"hi\\\"");
  CHECK(promescape::EscapeLabelValue("a\\b") == "a\\\\b");
  CHECK(promescape::EscapeLabelValue("line1\nline2") == "line1\\nline2");
  CHECK(promescape::EscapeLabelValue("\\\"\n") == "\\\\\\\"\\n");
  CHECK(promescape::EscapeLabelValue("") == "");
}

static void TestTraceEmitter() {
  // The kubeapi twin of telemetry.py's Chrome-JSON schema: slices and
  // instants dump as a parseable trace-event document with the keys
  // Perfetto / `tpuctl trace merge` need.
  kubeapi::TraceEmitter t;
  t.AddComplete("reconcile-pass", "reconcile", 100.0, 2500.0,
                {{"pass", "1"}, {"ok", "true"}});
  t.AddComplete("apply-object", "reconcile", 200.0, 30.0,
                {{"object", "20-plugin--daemonset.json"},
                 {"traceparent",
                  "00-0af7651916cd43dd8448eb211c80319c-"
                  "b7ad6b7169203331-01"}});
  t.AddInstant("drift-event", "watch", {{"object", "tpud"}});
  CHECK(t.size() == 3);
  std::string err;
  minijson::ValuePtr doc = minijson::Parse(t.DumpChromeJson(), &err);
  CHECK(doc && err.empty());
  minijson::ValuePtr events = doc->Get("traceEvents");
  CHECK(events && events->is_array() && events->elements().size() == 3);
  const auto& first = events->elements()[0];
  CHECK(first->PathString("name") == "reconcile-pass");
  CHECK(first->PathString("ph") == "X");
  CHECK(first->PathNumber("ts", -1) == 100.0);
  CHECK(first->PathNumber("dur", -1) == 2500.0);
  CHECK(first->PathNumber("pid", 0) == 1);
  CHECK(first->PathString("args.pass") == "1");
  const auto& instant = events->elements()[2];
  CHECK(instant->PathString("ph") == "i");
  CHECK(instant->PathString("s") == "t");
  CHECK(doc->PathString("otherData.producer") == "tpu-operator");
  CHECK(doc->PathNumber("otherData.epoch", 0) > 0);
  // bounded ring: overflowing kMaxEvents drops the oldest, keeps the
  // newest, and surfaces the drop count
  kubeapi::TraceEmitter full;
  for (size_t i = 0; i < kubeapi::TraceEmitter::kMaxEvents + 10; ++i)
    full.AddComplete("apply-object", "reconcile", double(i), 1.0, {});
  CHECK(full.size() <= kubeapi::TraceEmitter::kMaxEvents);
  CHECK(full.dropped() > 0);
  minijson::ValuePtr doc2 = minijson::Parse(full.DumpChromeJson(), &err);
  CHECK(doc2 != nullptr);
  CHECK(doc2->PathNumber("otherData.dropped_events", 0) > 0);
}

// Hostile chunked-transfer byte vectors (ISSUE 9): the shared
// Python<->C++ table for the TRUNCATE/GARBAGE fault classes
// (RetryableStatus pattern — tests/test_slowpath.py greps THESE raw
// strings out of this file and drives the identical bytes through the
// Python client's transport over a raw socket, asserting the same
// accept/reject verdicts). `ok` = the stream terminated cleanly and
// `decoded` is the payload; !ok = truncated/garbage, which the clients
// must classify as transport status 0, never as a short 200.
struct ChunkVector {
  const char* name;
  const char* raw;
  bool ok;
  const char* decoded;
};
static const ChunkVector kHostileChunkVectors[] = {
    {"clean", "2\r\n{}\r\n0\r\n\r\n", true, "{}"},
    {"clean-multi", "5\r\nhello\r\n6\r\n world\r\n0\r\n\r\n", true,
     "hello world"},
    {"empty-terminated", "0\r\n\r\n", true, ""},
    {"no-terminator", "5\r\nhello\r\n", false, ""},
    {"truncated-data", "40\r\n{\"type\":\"MODIFIED\",\"object\":{\"kind",
     false, ""},
    {"garbage-size", "zz\r\nhello\r\n0\r\n\r\n", false, ""},
    {"negative-size", "-5\r\nhello\r\n0\r\n\r\n", false, ""},
    {"empty", "", false, ""},
    {"bare-crlf", "\r\n", false, ""},
};

static void TestChunkedDecodeHostileVectors() {
  // Table-driven verdicts: every vector decodes (or is rejected) exactly
  // as pinned — the same verdicts the Python twin asserts over a live
  // socket.
  for (const auto& v : kHostileChunkVectors) {
    std::string out;
    bool ok = kubeclient::DecodeChunkedBody(v.raw, &out);
    if (ok != v.ok) {
      fprintf(stderr, "FAIL chunk vector %s: ok=%d want %d\n", v.name, ok,
              v.ok);
      ++g_failures;
    }
    if (ok && out != v.decoded) {
      fprintf(stderr, "FAIL chunk vector %s: decoded %s want %s\n", v.name,
              out.c_str(), v.decoded);
      ++g_failures;
    }
  }
  // Truncation fuzz: EVERY byte-prefix of every vector must decode
  // without crashing or over-reading, and a truncated CLEAN stream must
  // never report terminated with the wrong payload — cutting a valid
  // stream anywhere before its final chunk's size line yields !ok or a
  // strict prefix of the full payload.
  for (const auto& v : kHostileChunkVectors) {
    std::string raw = v.raw;
    for (size_t cut = 0; cut < raw.size(); ++cut) {
      std::string out;
      bool ok = kubeclient::DecodeChunkedBody(raw.substr(0, cut), &out);
      if (ok && v.ok) {
        std::string full = v.decoded;
        CHECK(out.size() <= full.size() &&
              full.compare(0, out.size(), out) == 0);
      }
    }
  }
  // Garbage fuzz: hostile filler bytes in place of sizes/payloads never
  // crash the decoder and never terminate a stream that lacks the
  // 0-length chunk. Explicit lengths so embedded NULs actually reach
  // the decoder (a const char* would strlen-truncate at the first one).
  const std::string fillers[] = {std::string("\x00\x01\x02", 3),
                                 std::string("\xff\xfe", 2),
                                 "GET / HTTP/1.1", "{\"json\":",
                                 "99999999999999999999\r\nx"};
  for (const std::string& f : fillers) {
    std::string out;
    CHECK(!kubeclient::DecodeChunkedBody(f, &out));
    CHECK(!kubeclient::DecodeChunkedBody(f + "\r\n", &out));
  }
}

static void TestWatchBackoff() {
  // Doubling from base, capped: the operand drift-watch reconnect
  // schedule. A persistently kClosed stream (each https open is a curl
  // spawn) must climb to the cap, never spin at full rate.
  CHECK(kubeclient::WatchBackoffMs(1, 1000, 30000) == 1000);
  CHECK(kubeclient::WatchBackoffMs(2, 1000, 30000) == 2000);
  CHECK(kubeclient::WatchBackoffMs(3, 1000, 30000) == 4000);
  CHECK(kubeclient::WatchBackoffMs(6, 1000, 30000) == 30000);  // capped
  // overflow safety: a day of consecutive failures still returns the cap
  CHECK(kubeclient::WatchBackoffMs(1000, 1000, 30000) == 30000);
  // degenerate inputs clamp instead of misbehaving
  CHECK(kubeclient::WatchBackoffMs(0, 1000, 30000) == 1000);
  CHECK(kubeclient::WatchBackoffMs(-5, 1000, 30000) == 1000);
  CHECK(kubeclient::WatchBackoffMs(3, 50000, 30000) == 30000);
  CHECK(kubeclient::WatchBackoffMs(3, 0, 30000) == 4);
  CHECK(kubeclient::WatchBackoffMs(3, 1000, 0) == 1);
}

static void TestWorkqueueSemantics() {
  // The rate-limited dedup queue (client-go util/workqueue analog): the
  // single-threaded contract checks live here; the threaded invariants
  // are hammered by grpcmin/stress_selftest.cc under TSan.
  workqueue::RateLimitedQueue q(0, 5, 100);
  std::string k;
  CHECK(!q.Get(&k, 0));  // empty: polls out immediately
  // dedup while queued: three Adds of one key = one Get
  q.Add("a");
  q.Add("a");
  q.Add("b");
  q.Add("a");
  CHECK(q.adds() == 4);   // adds meters pressure, not occupancy
  CHECK(q.depth() == 2);  // ...occupancy is deduped
  CHECK(q.Get(&k, 0) && k == "a");
  CHECK(q.Get(&k, 0) && k == "b");
  CHECK(!q.Get(&k, 0));
  q.Done("a");
  q.Done("b");
  CHECK(q.depth() == 0);  // a plain Done re-queues nothing
  // an Add while processing re-queues at Done (the blind-window fix:
  // an event landing mid-reconcile is never lost)
  q.Add("a");
  CHECK(q.depth() == 1);
  CHECK(q.Get(&k, 0) && k == "a");
  q.Add("a");             // a is processing: parked, not queued
  CHECK(q.depth() == 0);
  q.Done("a");
  CHECK(q.depth() == 1);  // re-queued by Done
  CHECK(q.Get(&k, 0) && k == "a");
  q.Done("a");
  // AddRateLimited: capped exponential strikes, Forget resets
  q.AddRateLimited("r");  // strike 1: 5ms
  CHECK(q.retries() == 1);
  CHECK(q.StrikesForTest("r") == 1);
  CHECK(q.depth() == 0);            // delayed, not queued
  CHECK(!q.Get(&k, 0));
  CHECK(q.Get(&k, 300) && k == "r");  // due after the delay
  q.Done("r");
  for (int i = 0; i < 8; ++i) q.AddRateLimited("r");
  CHECK(q.StrikesForTest("r") == 9);
  int due = q.NextDelayMs();
  CHECK(due >= 0 && due <= 100);  // capped at max_delay_ms
  CHECK(q.Get(&k, 300) && k == "r");
  q.Forget("r");
  q.Done("r");
  CHECK(q.StrikesForTest("r") == 0);
  // bounded depth: the OLDEST queued key sheds, resync flagged once
  workqueue::RateLimitedQueue small(2, 5, 100);
  small.Add("one");
  small.Add("two");
  CHECK(!small.TakeResyncNeeded());
  small.Add("three");  // sheds "one"
  CHECK(small.sheds() == 1);
  CHECK(small.depth() == 2);
  CHECK(small.TakeResyncNeeded());
  CHECK(!small.TakeResyncNeeded());  // exactly once
  CHECK(small.Get(&k, 0) && k == "two");
  CHECK(small.Get(&k, 0) && k == "three");
  // shutdown drains waiters
  small.ShutDown();
  CHECK(small.shutting_down());
  CHECK(!small.Get(&k, 0));
}

static void TestSubsetMatch() {
  // The informer cache's zero-request drift probe: desired ⊆ live, with
  // server-set fields (status, uid, resourceVersion) never counting as
  // drift and arrays comparing whole (merge-patch would revert reorders).
  auto J = [](const char* s) { return minijson::Parse(s); };
  auto want = J("{\"spec\": {\"replicas\": 2, \"labels\": {\"a\": \"b\"}},"
                " \"kind\": \"Deployment\"}");
  auto live = J("{\"spec\": {\"replicas\": 2, \"labels\": {\"a\": \"b\"},"
                " \"extra\": 1}, \"kind\": \"Deployment\","
                " \"status\": {\"readyReplicas\": 2},"
                " \"metadata\": {\"uid\": \"u1\"}}");
  CHECK(informer::SubsetMatch(*want, *live));
  CHECK(!informer::SubsetMatch(*live, *want));  // extra fields missing
  auto drift = J("{\"spec\": {\"replicas\": 3, \"labels\": {\"a\": \"b\"},"
                 " \"extra\": 1}, \"kind\": \"Deployment\"}");
  CHECK(!informer::SubsetMatch(*want, *drift));
  // arrays: exact length + elementwise
  CHECK(informer::SubsetMatch(*J("{\"a\": [1, 2]}"), *J("{\"a\": [1, 2]}")));
  CHECK(!informer::SubsetMatch(*J("{\"a\": [1, 2]}"), *J("{\"a\": [2, 1]}")));
  CHECK(!informer::SubsetMatch(*J("{\"a\": [1]}"), *J("{\"a\": [1, 2]}")));
  // scalars + null + type mismatches
  CHECK(informer::SubsetMatch(*J("{\"x\": null}"), *J("{\"x\": null}")));
  CHECK(!informer::SubsetMatch(*J("{\"x\": null}"), *J("{\"x\": 0}")));
  CHECK(!informer::SubsetMatch(*J("{\"x\": \"1\"}"), *J("{\"x\": 1}")));
}

int main() {
  TestJsonRoundtrip();
  TestJsonErrors();
  TestPaths();
  TestSweepCollections();
  TestReadiness();
  TestRetryClassification();
  TestOperandWorkloadTwinTable();
  TestFieldManagerTwin();
  TestOperatorMetricNamesTwinTable();
  TestOperatorTraceEventNamesTwinTable();
  TestTraceparentTwinsAndParsing();
  TestHistogramBucketBoundary();
  TestPromEscapeLabelValue();
  TestTraceEmitter();
  TestChunkedDecodeHostileVectors();
  TestWatchBackoff();
  TestWorkqueueSemantics();
  TestSubsetMatch();
  if (g_failures) {
    fprintf(stderr, "operator_selftest: %d FAILURES\n", g_failures);
    return 1;
  }
  printf("operator_selftest: all checks passed\n");
  return 0;
}
