// operator_selftest — unit checks for minijson + kubeapi + the watch
// reconnect backoff (no server needed).

#include <stdio.h>
#include <string.h>

#include "kubeapi.h"
#include "kubeclient.h"
#include "minijson.h"

static int g_failures = 0;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                    \
    }                                                                  \
  } while (0)

static void TestJsonRoundtrip() {
  const char* doc =
      "{\"a\": [1, 2.5, -3], \"b\": {\"c\": \"x\\ny\", \"d\": true},"
      " \"e\": null, \"uni\": \"\\u00e9\\u0041\"}";
  std::string err;
  auto v = minijson::Parse(doc, &err);
  CHECK(v && err.empty());
  CHECK(v->Path("b.c")->as_string() == "x\ny");
  CHECK(v->Path("b.d")->as_bool());
  CHECK(v->Path("e")->is_null());
  CHECK(v->Get("a")->elements().size() == 3);
  CHECK(v->Get("a")->elements()[1]->as_number() == 2.5);
  CHECK(v->Get("uni")->as_string() == "\xc3\xa9" "A");
  // dump -> reparse -> identical dump (canonical form fixpoint)
  std::string d1 = v->Dump();
  auto v2 = minijson::Parse(d1, &err);
  CHECK(v2 && v2->Dump() == d1);
  // integers stay integers through the double representation
  auto n = minijson::Parse("{\"x\": 123456789012}");
  CHECK(n->Dump() == "{\"x\":123456789012}");
}

static void TestJsonErrors() {
  std::string err;
  CHECK(!minijson::Parse("{", &err) && !err.empty());
  CHECK(!minijson::Parse("{\"a\": }", &err));
  CHECK(!minijson::Parse("[1, 2] trailing", &err));
  CHECK(!minijson::Parse("\"unterminated", &err));
  CHECK(!minijson::Parse("01x", &err));
  // strict number grammar: strtod-isms are malformed JSON
  CHECK(!minijson::Parse("inf", &err));
  CHECK(!minijson::Parse("{\"x\": nan}", &err));
  CHECK(!minijson::Parse("0x10", &err));
  CHECK(!minijson::Parse("01", &err));
  CHECK(!minijson::Parse("1.", &err));
  CHECK(!minijson::Parse("1e", &err));
  CHECK(!minijson::Parse("-", &err));
  CHECK(minijson::Parse("-0.5e-3", &err) != nullptr);
}

static minijson::ValuePtr Obj(const char* text) {
  std::string err;
  auto v = minijson::Parse(text, &err);
  if (!v) fprintf(stderr, "bad test object: %s\n", err.c_str());
  return v;
}

static void TestPaths() {
  std::string err;
  auto ds = Obj(
      "{\"apiVersion\": \"apps/v1\", \"kind\": \"DaemonSet\","
      " \"metadata\": {\"name\": \"tpud\", \"namespace\": \"tpu-system\"}}");
  CHECK(kubeapi::CollectionPath(*ds, &err) ==
        "/apis/apps/v1/namespaces/tpu-system/daemonsets");
  CHECK(kubeapi::ObjectPath(*ds, &err) ==
        "/apis/apps/v1/namespaces/tpu-system/daemonsets/tpud");

  auto ns = Obj(
      "{\"apiVersion\": \"v1\", \"kind\": \"Namespace\","
      " \"metadata\": {\"name\": \"tpu-system\"}}");
  CHECK(kubeapi::ObjectPath(*ns, &err) == "/api/v1/namespaces/tpu-system");

  auto svc = Obj(
      "{\"apiVersion\": \"v1\", \"kind\": \"Service\","
      " \"metadata\": {\"name\": \"m\", \"namespace\": \"x\"}}");
  CHECK(kubeapi::CollectionPath(*svc, &err) ==
        "/api/v1/namespaces/x/services");

  auto crb = Obj(
      "{\"apiVersion\": \"rbac.authorization.k8s.io/v1\","
      " \"kind\": \"ClusterRoleBinding\", \"metadata\": {\"name\": \"b\"}}");
  CHECK(kubeapi::ObjectPath(*crb, &err) ==
        "/apis/rbac.authorization.k8s.io/v1/clusterrolebindings/b");

  auto bogus = Obj("{\"apiVersion\": \"v1\", \"kind\": \"Wombat\","
                   " \"metadata\": {\"name\": \"w\"}}");
  CHECK(kubeapi::CollectionPath(*bogus, &err).empty() && !err.empty());
}

static void TestSweepCollections() {
  // Every managed kind (Plurals) except the never-labeled three must be
  // swept — a kind added to one table but not the other is the drift this
  // pin exists to catch. Count: 15 kinds - Namespace/Event/Pod = 12.
  auto colls = kubeapi::SweepCollections("tpu-system");
  CHECK(colls.size() == 12);
  auto has = [&](const char* want) {
    for (const auto& c : colls)
      if (c == want) return true;
    return false;
  };
  CHECK(has("/apis/apps/v1/namespaces/tpu-system/daemonsets"));
  CHECK(has("/apis/apps/v1/namespaces/tpu-system/statefulsets"));
  CHECK(has("/api/v1/namespaces/tpu-system/secrets"));
  CHECK(has("/apis/batch/v1/namespaces/tpu-system/jobs"));
  CHECK(has("/apis/rbac.authorization.k8s.io/v1/clusterroles"));
  CHECK(has("/apis/rbac.authorization.k8s.io/v1/namespaces/tpu-system/"
            "roles"));
  // empty namespace: only the cluster-scoped collections remain
  CHECK(kubeapi::SweepCollections("").size() == 2);
}

static void TestReadiness() {
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"status\": {}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\","
      " \"status\": {\"desiredNumberScheduled\": 2, \"numberReady\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\","
      " \"status\": {\"desiredNumberScheduled\": 2, \"numberReady\": 2}}")));
  // desired==0: not ready by default (no nodes matched yet)
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\","
      " \"status\": {\"desiredNumberScheduled\": 0, \"numberReady\": 0}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"spec\": {\"replicas\": 2},"
      " \"status\": {\"readyReplicas\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"spec\": {\"replicas\": 2},"
      " \"status\": {\"readyReplicas\": 2}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"Job\", \"status\": {\"succeeded\": 1}}")));
  CHECK(!kubeapi::IsReady(*Obj("{\"kind\": \"Job\", \"status\": {}}")));
  CHECK(kubeapi::IsReady(*Obj("{\"kind\": \"ConfigMap\"}")));

  // Upgrade semantics (kubectl rollout status parity): with generation
  // tracking, old-generation status or lagging updated counts gate readiness
  // even while the previous pods are still Ready.
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"metadata\": {\"generation\": 2},"
      " \"status\": {\"observedGeneration\": 1,"
      " \"desiredNumberScheduled\": 2, \"numberReady\": 2,"
      " \"updatedNumberScheduled\": 2}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"metadata\": {\"generation\": 2},"
      " \"status\": {\"observedGeneration\": 2,"
      " \"desiredNumberScheduled\": 2, \"numberReady\": 2,"
      " \"updatedNumberScheduled\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"DaemonSet\", \"metadata\": {\"generation\": 2},"
      " \"status\": {\"observedGeneration\": 2,"
      " \"desiredNumberScheduled\": 2, \"numberReady\": 2,"
      " \"updatedNumberScheduled\": 2}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"metadata\": {\"generation\": 3},"
      " \"spec\": {\"replicas\": 2},"
      " \"status\": {\"observedGeneration\": 2, \"readyReplicas\": 2,"
      " \"updatedReplicas\": 2}}")));
  CHECK(!kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"metadata\": {\"generation\": 3},"
      " \"spec\": {\"replicas\": 2},"
      " \"status\": {\"observedGeneration\": 3, \"readyReplicas\": 2,"
      " \"updatedReplicas\": 1}}")));
  CHECK(kubeapi::IsReady(*Obj(
      "{\"kind\": \"Deployment\", \"metadata\": {\"generation\": 3},"
      " \"spec\": {\"replicas\": 2},"
      " \"status\": {\"observedGeneration\": 3, \"readyReplicas\": 2,"
      " \"updatedReplicas\": 2}}")));
}

static void TestRetryClassification() {
  // The shared failure taxonomy (C++ twin of tpu_cluster.kubeapply's
  // RetryPolicy — the two tables must never drift): transport status 0
  // and 429/5xx-gateway statuses retry; everything else is success or
  // terminal. 409 Conflict is deliberately NOT retryable — the apply path
  // resolves it semantically (re-GET then re-PATCH).
  const int retryable[] = {0, 429, 500, 502, 503, 504};
  for (int s : retryable) CHECK(kubeclient::RetryableStatus(s));
  const int not_retryable[] = {200, 201, 202, 301, 400, 401, 403,
                               404,  409, 410, 422, 501};
  for (int s : not_retryable) CHECK(!kubeclient::RetryableStatus(s));

  // Retry-After parsing (plain-http transport, lowercased header block):
  // seconds — integer or fractional — to ms; absent, the http-date form,
  // or garbage parse to 0 (caller falls back to computed backoff); a
  // hostile/buggy value clamps to an hour.
  CHECK(kubeclient::ParseRetryAfterMs(
            "content-type: application/json\r\nretry-after: 2") == 2000);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after:0.25") == 250);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after:  7\r\nx: y") == 7000);
  CHECK(kubeclient::ParseRetryAfterMs("content-type: text/plain") == 0);
  CHECK(kubeclient::ParseRetryAfterMs(
            "retry-after: wed, 21 oct 2026 07:28:00 gmt") == 0);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after: -5") == 0);
  CHECK(kubeclient::ParseRetryAfterMs("retry-after: 999999") == 3600000);
}

static void TestOperandWorkloadTwinTable() {
  // Pinned twin table (same pattern as TestRetryClassification): the
  // kinds the operator drift-watches as operand workloads must be
  // exactly the GVKs the Python bundle linter treats as operand
  // workloads — tpu_cluster/lint.py OPERAND_WORKLOAD_KINDS pins
  // (apps/v1, DaemonSet) and (apps/v1, Deployment); tests/test_lint.py
  // greps THIS table out of kubeapi.cc to close the loop without a
  // compiler.
  const auto& kinds = kubeapi::OperandWorkloadKinds();
  CHECK(kinds.size() == 2);
  auto has = [&](const char* want) {
    for (const auto& k : kinds)
      if (k == want) return true;
    return false;
  };
  CHECK(has("DaemonSet"));
  CHECK(has("Deployment"));
  // the apiVersion half of the GVK twin: both kinds resolve to apps/v1
  // collections through the same Plurals/ApiVersions tables the operator
  // applies with
  for (const auto& k : kinds) {
    std::string err;
    auto obj = Obj(("{\"apiVersion\": \"apps/v1\", \"kind\": \"" + k +
                    "\", \"metadata\": {\"name\": \"x\", \"namespace\": "
                    "\"ns\"}}")
                       .c_str());
    std::string coll = kubeapi::CollectionPath(*obj, &err);
    CHECK(coll.rfind("/apis/apps/v1/", 0) == 0);
  }
}

static void TestFieldManagerTwin() {
  // The field-manager twin table (RetryableStatus pattern): the name the
  // operator applies under is pinned here and grep-pinned from Python
  // (tests/test_apply.py checks kubeapi.cc's initializer equals
  // kubeapply.OPERATOR_FIELD_MANAGER, and that it differs from the
  // CLI's "tpuctl"). Per-field ownership means a silent rename orphans
  // every field the deployed fleet's operators own.
  CHECK(strcmp(kubeapi::FieldManager(), "tpu-operator") == 0);
  CHECK(strcmp(kubeapi::FieldManager(), "tpuctl") != 0);
}

static void TestOperatorMetricNamesTwinTable() {
  // Pinned twin table (RetryableStatus pattern): the families the
  // operator's /metrics endpoint must emit — tpu_cluster/telemetry.py
  // OPERATOR_METRIC_NAMES names the same set, tests/test_telemetry.py
  // greps THIS table out of kubeapi.cc to close the loop without a
  // compiler, and `tpuctl verify --config operator-metrics` gates the
  // live scrape. A rename lands here before it lands on a dashboard.
  const auto& names = kubeapi::OperatorMetricNames();
  CHECK(names.size() == 9);
  auto has = [&](const char* want) {
    for (const auto& n : names)
      if (n == want) return true;
    return false;
  };
  CHECK(has("tpu_operator_objects"));
  CHECK(has("tpu_operator_passes_total"));
  CHECK(has("tpu_operator_healthy"));
  CHECK(has("tpu_operator_consecutive_failures"));
  CHECK(has("tpu_operator_policy_generation"));
  CHECK(has("tpu_operator_reconcile_duration_seconds"));
  CHECK(has("tpu_operator_watch_reconnects_total"));
  CHECK(has("tpu_operator_queue_depth"));
  CHECK(has("tpu_operator_sync_lag_seconds"));
  // uniqueness + the namespace prefix every family must carry
  for (size_t i = 0; i < names.size(); ++i) {
    CHECK(names[i].rfind("tpu_operator_", 0) == 0);
    for (size_t j = i + 1; j < names.size(); ++j)
      CHECK(names[i] != names[j]);
  }
}

static void TestWatchBackoff() {
  // Doubling from base, capped: the operand drift-watch reconnect
  // schedule. A persistently kClosed stream (each https open is a curl
  // spawn) must climb to the cap, never spin at full rate.
  CHECK(kubeclient::WatchBackoffMs(1, 1000, 30000) == 1000);
  CHECK(kubeclient::WatchBackoffMs(2, 1000, 30000) == 2000);
  CHECK(kubeclient::WatchBackoffMs(3, 1000, 30000) == 4000);
  CHECK(kubeclient::WatchBackoffMs(6, 1000, 30000) == 30000);  // capped
  // overflow safety: a day of consecutive failures still returns the cap
  CHECK(kubeclient::WatchBackoffMs(1000, 1000, 30000) == 30000);
  // degenerate inputs clamp instead of misbehaving
  CHECK(kubeclient::WatchBackoffMs(0, 1000, 30000) == 1000);
  CHECK(kubeclient::WatchBackoffMs(-5, 1000, 30000) == 1000);
  CHECK(kubeclient::WatchBackoffMs(3, 50000, 30000) == 30000);
  CHECK(kubeclient::WatchBackoffMs(3, 0, 30000) == 4);
  CHECK(kubeclient::WatchBackoffMs(3, 1000, 0) == 1);
}

int main() {
  TestJsonRoundtrip();
  TestJsonErrors();
  TestPaths();
  TestSweepCollections();
  TestReadiness();
  TestRetryClassification();
  TestOperandWorkloadTwinTable();
  TestFieldManagerTwin();
  TestOperatorMetricNamesTwinTable();
  TestWatchBackoff();
  if (g_failures) {
    fprintf(stderr, "operator_selftest: %d FAILURES\n", g_failures);
    return 1;
  }
  printf("operator_selftest: all checks passed\n");
  return 0;
}
