// workqueue — the operator's rate-limited, deduplicating reconcile queue
// (client-go util/workqueue analog, the half of the controller-runtime
// core that decides WHEN a key is reconciled).
//
// Semantics, pinned by native/operator/selftest.cc and hammered under
// threads by native/grpcmin/stress_selftest.cc (plain + TSan):
//
//  - Dedup while queued: Add() of a key already waiting is a no-op for
//    the queue (the adds counter still moves — it meters pressure, not
//    occupancy). A key Add()ed while PROCESSING is re-queued when Done()
//    is called, so an event landing mid-reconcile is never lost — this
//    is what replaced the operator's pass->watch blind-window LIST.
//  - Per-item backoff: AddRateLimited() re-queues a failed key after a
//    capped exponential delay (base << strikes, never above cap);
//    Forget() resets the key's strike count on success.
//  - Bounded depth: beyond max_depth the OLDEST queued key is shed and
//    the queue flags resync_needed — the caller repairs the loss with
//    one full-resync enqueue instead of growing without bound
//    (shed-oldest-resync, the informer's relist being the backstop).
//  - Thread-safe (mutex + condvar). The operator itself is
//    single-threaded by contract and polls with Get(wait_ms=0); the
//    locking exists so the concurrency stress selftest can prove the
//    invariants under real contention.

#ifndef TPU_NATIVE_OPERATOR_WORKQUEUE_H_
#define TPU_NATIVE_OPERATOR_WORKQUEUE_H_

#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <vector>

namespace workqueue {

class RateLimitedQueue {
 public:
  // max_depth 0 = unbounded. Delays in milliseconds.
  explicit RateLimitedQueue(size_t max_depth = 0, int base_delay_ms = 5,
                            int max_delay_ms = 30000);

  // Queue `key` for processing (deduplicated; see header comment).
  void Add(const std::string& key);

  // Re-queue a failed key after its per-key capped exponential backoff.
  // Each call is one strike (and one tick of the retries counter).
  void AddRateLimited(const std::string& key);

  // Queue `key` after a fixed delay (readiness follow-up, not a strike).
  void AddAfter(const std::string& key, int delay_ms);

  // Clear `key`'s strike count (reconcile succeeded).
  void Forget(const std::string& key);

  // Pop the next key; blocks up to wait_ms (0 = poll). False on timeout
  // or shutdown. The key stays marked processing until Done().
  bool Get(std::string* key, int wait_ms);

  // Processing finished; a key re-Add()ed meanwhile goes back on queue.
  void Done(const std::string& key);

  void ShutDown();
  bool shutting_down() const;

  // Milliseconds until the earliest delayed key is due (-1 = none
  // pending). The single-threaded operator uses this to size its idle
  // sleep instead of busy-polling Get(0).
  int NextDelayMs() const;

  // Counters for the tpu_operator_workqueue_* families.
  long long adds() const;     // every Add/AddRateLimited/AddAfter call
  long long retries() const;  // AddRateLimited calls
  size_t depth() const;       // keys queued now (excludes delayed)
  size_t sheds() const;       // keys dropped by the depth bound

  // True exactly once after a shed: the caller owes a full resync.
  bool TakeResyncNeeded();

  int StrikesForTest(const std::string& key) const;

 private:
  using Clock = std::chrono::steady_clock;

  // caller holds mu_: move due delayed keys onto the active queue
  void PromoteDueLocked(Clock::time_point now);
  // caller holds mu_: enqueue with dedup + depth bound
  void AddLocked(const std::string& key);

  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  std::set<std::string> dirty_;       // queued or awaiting re-queue
  std::set<std::string> processing_;  // handed out via Get()
  std::map<std::string, int> strikes_;
  // delayed keys, kept sorted by due time (small N: the operator's
  // retry/readiness follow-ups, not the hot path)
  std::multimap<Clock::time_point, std::string> delayed_;
  size_t max_depth_;
  int base_delay_ms_, max_delay_ms_;
  bool shutting_down_ = false;
  bool resync_needed_ = false;
  long long adds_ = 0, retries_ = 0;
  size_t sheds_ = 0;
};

}  // namespace workqueue

#endif  // TPU_NATIVE_OPERATOR_WORKQUEUE_H_
