#include "informer.h"

#include <string.h>

namespace informer {

namespace {

double SecondsSince(const struct timespec& ref) {
  // direct timespec math, NOT ElapsedMs: the int-milliseconds return
  // overflows after ~24.8 days — exactly the long-outage case staleness
  // exists to expose
  struct timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  double s = static_cast<double>(now.tv_sec - ref.tv_sec) +
             (now.tv_nsec - ref.tv_nsec) / 1e9;
  return s < 0 ? 0 : s;
}

}  // namespace

bool SubsetMatch(const minijson::Value& want, const minijson::Value& have) {
  using minijson::Value;
  if (want.type() != have.type()) return false;
  switch (want.type()) {
    case Value::Type::kNull:
      return true;
    case Value::Type::kBool:
      return want.as_bool() == have.as_bool();
    case Value::Type::kNumber:
      return want.as_number() == have.as_number();
    case Value::Type::kString:
      return want.as_string() == have.as_string();
    case Value::Type::kArray: {
      const auto& w = want.elements();
      const auto& h = have.elements();
      // arrays compare whole: list merge semantics (reorder, append) are
      // a drift the operator's merge-patch would revert, so report them
      if (w.size() != h.size()) return false;
      for (size_t i = 0; i < w.size(); ++i)
        if (!w[i] || !h[i] || !SubsetMatch(*w[i], *h[i])) return false;
      return true;
    }
    case Value::Type::kObject: {
      for (const auto& kv : want.items()) {
        minijson::ValuePtr hv = have.Get(kv.first);
        if (!kv.second || !hv || !SubsetMatch(*kv.second, *hv))
          return false;
      }
      return true;
    }
  }
  return false;
}

Informer::Informer(const kubeclient::Config* cfg, std::string collection,
                   int page_limit, int window_s)
    : cfg_(cfg),
      coll_(std::move(collection)),
      page_limit_(page_limit < 1 ? 1 : page_limit),
      window_s_(window_s < 1 ? 1 : window_s) {
  clock_gettime(CLOCK_MONOTONIC, &fresh_at_);
}

Informer::~Informer() { Close(); }

void Informer::Close() { ws_.Close(); }

minijson::ValuePtr Informer::GetObject(const std::string& name) const {
  auto it = cache_.find(name);
  return it == cache_.end() ? nullptr : it->second;
}

void Informer::Touch() { clock_gettime(CLOCK_MONOTONIC, &fresh_at_); }

double Informer::StalenessSeconds() const { return SecondsSince(fresh_at_); }

void Informer::BackOff() {
  ++strikes_;
  clock_gettime(CLOCK_MONOTONIC, &blocked_at_);
  backoff_ms_ = kubeclient::WatchBackoffMs(strikes_, 1000, 30000);
  ws_.Close();
  ++reconnects_;
}

bool Informer::Resync(std::string* err) {
  std::map<std::string, minijson::ValuePtr> fresh;
  std::string cont, rv;
  int pages = 0;
  bool restarted = false;
  for (;;) {
    std::string q = coll_ + "?limit=" + std::to_string(page_limit_);
    if (!cont.empty()) q += "&continue=" + cont;
    kubeclient::Response r = kubeclient::Call(*cfg_, "GET", q);
    if (r.status == 410) {
      // continue token expired mid-chase: restart the LIST from a clean
      // first page, at most once (apiserver chunked-LIST semantics — a
      // second 410 means the server can't serve a consistent list)
      if (restarted) {
        *err = "paginated LIST " + coll_ + ": continue expired twice";
        return false;
      }
      restarted = true;
      fresh.clear();
      cont.clear();
      pages = 0;
      continue;
    }
    if (!r.ok()) {
      *err = "LIST " + q + " -> " + std::to_string(r.status) + " " +
             (r.status ? r.body.substr(0, 160) : r.error);
      return false;
    }
    minijson::ValuePtr doc = minijson::Parse(r.body);
    minijson::ValuePtr items = doc ? doc->Get("items") : nullptr;
    if (!items || !items->is_array()) {
      *err = "LIST " + coll_ + ": reply without items[]";
      return false;
    }
    ++pages;
    for (const auto& item : items->elements()) {
      std::string name = item->PathString("metadata.name");
      if (!name.empty()) fresh[name] = item;
    }
    rv = doc->PathString("metadata.resourceVersion", rv);
    cont = doc->PathString("metadata.continue");
    if (cont.empty()) break;
  }
  cache_ = std::move(fresh);
  rv_ = rv;
  pages_last_list_ = pages;
  ++relists_;
  synced_ = true;
  strikes_ = 0;
  backoff_ms_ = 0;
  Touch();
  // any stream opened before this list is a stale cursor: drop it so the
  // next Pump resumes from the fresh resourceVersion
  ws_.Close();
  return true;
}

int Informer::Pump(const std::function<void(const Event&)>& on_event) {
  if (!synced_) return 0;
  if (!ws_.is_open()) {
    if (backoff_ms_ > 0 &&
        kubeclient::ElapsedMs(blocked_at_) < backoff_ms_)
      return 0;
    std::string err;
    std::string path =
        coll_ + "?watch=1&timeoutSeconds=" + std::to_string(window_s_);
    if (!rv_.empty()) path += "&resourceVersion=" + rv_;
    clock_gettime(CLOCK_MONOTONIC, &opened_at_);
    if (!ws_.Open(*cfg_, path, window_s_ + 30, &err)) {
      BackOff();
      return 0;
    }
    backoff_ms_ = 0;
  }
  // Bounded drain: a saturating stream must hand control back so the
  // caller can serve its status listener and the other informers.
  constexpr int kMaxDrain = 64;
  int delivered = 0;
  for (int drained = 0; drained < kMaxDrain; ++drained) {
    std::string line;
    kubeclient::WatchStream::Result r = ws_.Next(0, &line);
    if (r == kubeclient::WatchStream::kTimeout) break;
    if (r == kubeclient::WatchStream::kClosed ||
        r == kubeclient::WatchStream::kError) {
      bool clean = r == kubeclient::WatchStream::kClosed &&
                   kubeclient::ElapsedMs(opened_at_) >=
                       window_s_ * 1000 - 1500;
      if (clean) {
        // the server served the whole timeoutSeconds window and closed
        // it properly: the cache is provably fresh as of now; re-watch
        // from the held resourceVersion at full rate, NO re-LIST
        Touch();
        strikes_ = 0;
        backoff_ms_ = 0;
        ws_.Close();
      } else {
        // quick close / transport break: capped exponential backoff —
        // a rejecting proxy must not tight-loop stream opens
        BackOff();
      }
      break;
    }
    minijson::ValuePtr ev = minijson::Parse(line);
    std::string type =
        ev && ev->Get("type") ? ev->Get("type")->as_string() : "";
    minijson::ValuePtr obj = ev ? ev->Get("object") : nullptr;
    if (!ev || type == "ERROR" || !obj || !obj->Get("metadata")) {
      // Watch-level ERROR (410 Expired after a flap) or junk the https
      // transport echoed as lines: the cursor is dead. Exactly ONE
      // paginated re-LIST rebuilds the cache, then the stream resumes
      // from the fresh resourceVersion. A failing re-LIST backs off and
      // keeps the previous cache (the interval resync retries).
      ws_.Close();
      std::string err;
      if (!Resync(&err)) BackOff();
      break;
    }
    std::string name = obj->PathString("metadata.name");
    if (name.empty()) continue;
    if (type == "DELETED") {
      cache_.erase(name);
    } else {
      cache_[name] = obj;
      std::string rv = obj->PathString("metadata.resourceVersion");
      if (!rv.empty()) rv_ = rv;
    }
    ++events_;
    Touch();
    if (on_event) {
      Event e;
      e.type = type;
      e.name = name;
      e.object = obj;
      on_event(e);
    }
    ++delivered;
  }
  return delivered;
}

}  // namespace informer
