// informer — a shared per-collection LIST+watch cache for the operator
// (client-go SharedInformer analog; C++ twin of tpu_cluster/informer.py).
//
// One Informer owns one collection path. Resync() performs the paginated
// initial LIST (`?limit=N` + `continue=` chase, restarted at most once
// when a continue token expires with 410); Pump() drains the streaming
// `?watch=1` connection WITHOUT blocking, maintaining the name->object
// cache and resourceVersion cursor. After the initial sync, steady state
// costs zero reads: the stream is the only traffic, and a clean
// timeoutSeconds window expiry re-watches from the held resourceVersion
// with NO re-LIST. A watch-level ERROR (410 Expired after an apiserver
// flap, or an error body echoed as event lines) costs exactly ONE
// paginated re-LIST, then the stream resumes from the fresh
// resourceVersion — O(events), never O(objects x passes).
//
// Unlike the threaded Python twin, this informer is single-threaded and
// cooperatively pumped (the operator's status listener must be served
// between drains); every request goes through kubeclient::Call /
// WatchStream::Open and inherits their whole-attempt walls.

#ifndef TPU_NATIVE_OPERATOR_INFORMER_H_
#define TPU_NATIVE_OPERATOR_INFORMER_H_

#include <time.h>

#include <functional>
#include <map>
#include <string>

#include "kubeclient.h"
#include "minijson.h"

namespace informer {

// One cache mutation, delivered from Pump(): type is the wire event type
// ("MODIFIED"/"DELETED"); object is the full current object for MODIFIED
// and the skeleton `{"metadata": {"name": ...}}` payload for DELETED.
struct Event {
  std::string type;
  std::string name;
  minijson::ValuePtr object;
};

// True when every field `want` specifies is present and equal in `have`:
// objects recurse per key, arrays must match in length and element-wise,
// scalars compare exactly. The cache-resident drift probe — a desired
// manifest that SubsetMatch()es the cached live object needs no apply
// (server-set fields the manifest doesn't mention never count as drift).
bool SubsetMatch(const minijson::Value& want, const minijson::Value& have);

class Informer {
 public:
  // cfg must outlive the informer. window_s is the watch timeoutSeconds
  // — also the staleness bound a healthy idle stream guarantees (each
  // clean window expiry proves the server was reachable through it).
  Informer(const kubeclient::Config* cfg, std::string collection,
           int page_limit = 200, int window_s = 30);
  ~Informer();

  // Paginated LIST replacing the whole cache. False (with *err) when the
  // apiserver is unreachable or replies garbage; the previous cache and
  // resourceVersion are kept so the caller can retry.
  bool Resync(std::string* err);

  // Drain available watch events into the cache, (re)opening the stream
  // as due (capped exponential backoff after abnormal closes). Never
  // blocks; returns the number of events delivered to on_event this
  // call. No-op before the first successful Resync().
  int Pump(const std::function<void(const Event&)>& on_event);

  void Close();

  bool synced() const { return synced_; }
  bool stream_open() const { return ws_.is_open(); }
  const std::string& collection() const { return coll_; }
  const std::map<std::string, minijson::ValuePtr>& objects() const {
    return cache_;
  }
  // nullptr when absent.
  minijson::ValuePtr GetObject(const std::string& name) const;

  long long relists() const { return relists_; }
  long long events() const { return events_; }
  // abnormal-close reopens + failed opens (quick-close churn); a stream
  // cleanly idling out its window does not count
  long long reconnects() const { return reconnects_; }
  int pages_last_list() const { return pages_last_list_; }

  // Seconds since this cache was last PROVEN fresh: a completed list,
  // a delivered event, or a clean watch-window expiry. The
  // tpu_operator_sync_lag_seconds source — bounded by ~window_s on a
  // healthy stream, growing without bound when the apiserver is gone.
  double StalenessSeconds() const;

 private:
  void Touch();
  void BackOff();

  const kubeclient::Config* cfg_;
  std::string coll_;
  int page_limit_;
  int window_s_;

  kubeclient::WatchStream ws_;
  std::map<std::string, minijson::ValuePtr> cache_;
  std::string rv_;  // resourceVersion cursor (list reply / event objects)
  bool synced_ = false;

  int strikes_ = 0;    // consecutive abnormal closes / failed opens
  int backoff_ms_ = 0; // 0 = may (re)open immediately
  struct timespec opened_at_ = {0, 0};
  struct timespec blocked_at_ = {0, 0};
  struct timespec fresh_at_ = {0, 0};  // StalenessSeconds anchor

  long long relists_ = 0;
  long long events_ = 0;
  long long reconnects_ = 0;
  int pages_last_list_ = 0;
};

}  // namespace informer

#endif  // TPU_NATIVE_OPERATOR_INFORMER_H_
