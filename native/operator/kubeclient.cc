#include "kubeclient.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <vector>

namespace kubeclient {

bool ReadFileTrim(const std::string& path, std::string* out) {
  FILE* f = fopen(path.c_str(), "r");
  if (!f) return false;
  char buf[8192];
  out->clear();
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), f)) > 0) out->append(buf, n);
  fclose(f);
  while (!out->empty() && (out->back() == '\n' || out->back() == '\r'))
    out->pop_back();
  return true;
}

namespace {

struct Url {
  bool https = false;
  std::string host;
  int port = 80;
  std::string base_path;  // mount prefix, e.g. "/k8s" behind a proxy
};

bool ParseUrl(const std::string& url, Url* out, std::string* err) {
  std::string rest;
  if (url.rfind("http://", 0) == 0) {
    out->https = false;
    out->port = 80;
    rest = url.substr(7);
  } else if (url.rfind("https://", 0) == 0) {
    out->https = true;
    out->port = 443;
    rest = url.substr(8);
  } else {
    *err = "base_url must start with http:// or https://";
    return false;
  }
  size_t slash = rest.find('/');
  if (slash != std::string::npos) {
    out->base_path = rest.substr(slash);
    while (!out->base_path.empty() && out->base_path.back() == '/')
      out->base_path.pop_back();
    rest = rest.substr(0, slash);
  }
  if (!rest.empty() && rest[0] == '[') {
    // bracketed IPv6 literal: [::1] or [::1]:8001
    size_t close = rest.find(']');
    if (close == std::string::npos) {
      *err = "unterminated '[' in base_url host";
      return false;
    }
    out->host = rest.substr(1, close - 1);
    if (close + 1 < rest.size() && rest[close + 1] == ':')
      out->port = atoi(rest.c_str() + close + 2);
  } else {
    size_t colon = rest.rfind(':');
    if (colon != std::string::npos) {
      out->port = atoi(rest.c_str() + colon + 1);
      rest = rest.substr(0, colon);
    }
    out->host = rest;
  }
  if (out->host.empty()) {
    *err = "empty host in base_url";
    return false;
  }
  return true;
}

// ------------------------------------------------------------------ plain http

int ConnectTcp(const std::string& host, int port, int timeout_ms,
               std::string* err) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  int rc = getaddrinfo(host.c_str(), portstr, &hints, &res);
  if (rc != 0) {
    *err = std::string("resolve ") + host + ": " + gai_strerror(rc);
    return -1;
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, SOCK_STREAM, 0);
    if (fd < 0) continue;
    // non-blocking connect with timeout
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    rc = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      struct pollfd pfd = {fd, POLLOUT, 0};
      rc = poll(&pfd, 1, timeout_ms) == 1 ? 0 : -1;
      if (rc == 0) {
        int soerr = 0;
        socklen_t len = sizeof(soerr);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &len);
        if (soerr != 0) rc = -1;
      }
    }
    if (rc == 0) {
      fcntl(fd, F_SETFL, flags);
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0 && err->empty()) *err = "connect failed: " + host;
  return fd;
}

Response PlainHttp(const Config& cfg, const Url& url,
                   const std::string& method, const std::string& path,
                   const std::string& body,
                   const std::string& content_type) {
  Response resp;
  std::string err;
  int fd = ConnectTcp(url.host, url.port, cfg.timeout_ms, &err);
  if (fd < 0) {
    resp.error = err;
    return resp;
  }
  std::string req = method + " " + url.base_path + path + " HTTP/1.1\r\n" +
                    "Host: " + url.host + "\r\n" +
                    "Connection: close\r\nAccept: application/json\r\n";
  if (!cfg.user_agent.empty())
    req += "User-Agent: " + cfg.user_agent + "\r\n";
  if (!cfg.token.empty()) req += "Authorization: Bearer " + cfg.token + "\r\n";
  if (!body.empty()) {
    req += "Content-Type: " + content_type + "\r\n";
    req += "Content-Length: " + std::to_string(body.size()) + "\r\n";
  }
  req += "\r\n" + body;

  size_t off = 0;
  while (off < req.size()) {
    ssize_t n = write(fd, req.data() + off, req.size() - off);
    if (n <= 0) {
      resp.error = "write failed";
      close(fd);
      return resp;
    }
    off += n;
  }
  std::string raw;
  char buf[8192];
  // timeout_ms bounds the WHOLE response, not each poll — a server
  // trickling bytes must not stall the single-threaded caller forever.
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  while (true) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    int left = cfg.timeout_ms -
               static_cast<int>((now.tv_sec - t0.tv_sec) * 1000 +
                                (now.tv_nsec - t0.tv_nsec) / 1000000);
    struct pollfd pfd = {fd, POLLIN, 0};
    if (left <= 0 || poll(&pfd, 1, left) != 1) {
      resp.error = "read timeout";
      close(fd);
      return resp;
    }
    ssize_t n = read(fd, buf, sizeof(buf));
    if (n < 0) {
      resp.error = "read failed";
      close(fd);
      return resp;
    }
    if (n == 0) break;
    raw.append(buf, n);
  }
  close(fd);

  size_t hdr_end = raw.find("\r\n\r\n");
  if (raw.compare(0, 5, "HTTP/") != 0 || hdr_end == std::string::npos) {
    resp.error = "malformed HTTP response";
    return resp;
  }
  // The status code sits after the first space WITHIN the status line; a
  // truncated/malformed reply without one must be a loud parse error, not
  // atoi("HTTP/...") (find() past the line would wrap npos+1 to 0).
  size_t line_end = raw.find("\r\n");
  size_t sp = raw.find(' ');
  if (sp == std::string::npos || sp > line_end) {
    resp.error = "malformed HTTP status line";
    return resp;
  }
  resp.status = atoi(raw.c_str() + sp + 1);
  std::string headers = raw.substr(0, hdr_end);
  resp.body = raw.substr(hdr_end + 4);
  // Connection: close => body runs to EOF, but honor chunked encoding from
  // picky servers.
  for (char& c : headers) c = tolower(c);
  resp.retry_after_ms = ParseRetryAfterMs(headers);
  if (headers.find("transfer-encoding: chunked") != std::string::npos) {
    std::string decoded;
    if (!DecodeChunkedBody(resp.body, &decoded)) {
      // A chunked body that ends without the 0-length chunk (or whose
      // size lines are garbage) was cut off mid-stream; silently
      // returning the prefix would hand truncated JSON to the
      // reconciler.
      resp.status = 0;
      resp.body.clear();
      resp.error = "truncated chunked HTTP body";
      return resp;
    }
    resp.body = decoded;
  }
  return resp;
}

// ------------------------------------------------------------------ curl https

Response CurlHttps(const Config& cfg, const std::string& method,
                   const std::string& url, const std::string& body,
                   const std::string& content_type) {
  Response resp;
  if (cfg.ca_file.empty() && !cfg.insecure_skip_tls_verify) {
    resp.error =
        "refusing unverified https to " + cfg.base_url +
        ": no CA file; pass --ca-file or --insecure-skip-tls-verify";
    return resp;
  }
  char body_path[] = "/tmp/tpuop-body-XXXXXX";
  int body_fd = -1;
  if (!body.empty()) {
    body_fd = mkstemp(body_path);
    if (body_fd < 0 || write(body_fd, body.data(), body.size()) !=
                           static_cast<ssize_t>(body.size())) {
      resp.error = "cannot stage request body";
      // a short write still created the file — unlink it on the way out
      if (body_fd >= 0) { close(body_fd); unlink(body_path); }
      return resp;
    }
  }
  // The bearer token must never appear on the argv (readable by any
  // process via /proc/<pid>/cmdline); pass it via a 0600 header file.
  char hdr_path[] = "/tmp/tpuop-hdr-XXXXXX";
  int hdr_fd = -1;
  if (!cfg.token.empty()) {
    hdr_fd = mkstemp(hdr_path);
    std::string hdr = "Authorization: Bearer " + cfg.token + "\n";
    if (hdr_fd < 0 || write(hdr_fd, hdr.data(), hdr.size()) !=
                          static_cast<ssize_t>(hdr.size())) {
      resp.error = "cannot stage auth header";
      // never leave a partial Authorization line on disk
      if (hdr_fd >= 0) { close(hdr_fd); unlink(hdr_path); }
      if (body_fd >= 0) { close(body_fd); unlink(body_path); }
      return resp;
    }
  }

  std::vector<std::string> args = {
      "curl", "-sS", "-X", method, "--max-time",
      std::to_string((cfg.timeout_ms + 999) / 1000),
      // status on the last line of stdout, separated for parsing
      "-w", "\n%{http_code}",
      "-H", "Accept: application/json",
  };
  if (!cfg.user_agent.empty())
    args.insert(args.end(), {"-A", cfg.user_agent});
  if (hdr_fd >= 0)
    args.insert(args.end(), {"-H", std::string("@") + hdr_path});
  if (!cfg.ca_file.empty()) {
    args.insert(args.end(), {"--cacert", cfg.ca_file});
  } else {
    // Reachable only with insecure_skip_tls_verify (gated at entry above).
    static bool warned = false;
    if (!warned) {
      warned = true;
      fprintf(stderr,
              "kubeclient: WARNING: TLS verification DISABLED for %s "
              "(insecure-skip-tls-verify)\n", cfg.base_url.c_str());
    }
    args.push_back("-k");
  }
  if (!body.empty()) {
    args.insert(args.end(), {"-H", "Content-Type: " + content_type,
                             "--data-binary", std::string("@") + body_path});
  }
  args.push_back(url);

  auto cleanup_temps = [&]() {
    if (body_fd >= 0) { close(body_fd); unlink(body_path); }
    if (hdr_fd >= 0) { close(hdr_fd); unlink(hdr_path); }
  };

  int pipefd[2];
  if (pipe(pipefd) != 0) {
    resp.error = "pipe failed";
    cleanup_temps();
    return resp;
  }
  pid_t pid = fork();
  if (pid < 0) {
    resp.error = "fork failed";
    close(pipefd[0]);
    close(pipefd[1]);
    cleanup_temps();
    return resp;
  }
  if (pid == 0) {
    dup2(pipefd[1], 1);
    close(pipefd[0]);
    close(pipefd[1]);
    std::vector<char*> argv;
    for (auto& a : args) argv.push_back(const_cast<char*>(a.c_str()));
    argv.push_back(nullptr);
    execvp("curl", argv.data());
    _exit(127);
  }
  close(pipefd[1]);
  std::string out;
  char buf[8192];
  ssize_t n;
  while ((n = read(pipefd[0], buf, sizeof(buf))) > 0) out.append(buf, n);
  close(pipefd[0]);
  int wstatus = 0;
  waitpid(pid, &wstatus, 0);
  cleanup_temps();
  if (!WIFEXITED(wstatus) || WEXITSTATUS(wstatus) != 0) {
    resp.error = "curl exited " + std::to_string(WEXITSTATUS(wstatus)) +
                 ": " + out.substr(0, 200);
    return resp;
  }
  size_t nl = out.rfind('\n');
  if (nl == std::string::npos) {
    resp.error = "curl produced no status line";
    return resp;
  }
  resp.status = atoi(out.c_str() + nl + 1);
  resp.body = out.substr(0, nl);
  return resp;
}

// One transport round trip, no retries — Call() owns the retry loop.
Response CallOnce(const Config& cfg, const std::string& method,
                  const std::string& path, const std::string& body,
                  const std::string& content_type) {
  Url url;
  Response resp;
  if (!ParseUrl(cfg.base_url, &url, &resp.error)) return resp;
  if (url.https)
    return CurlHttps(cfg, method, cfg.base_url + path, body, content_type);
  return PlainHttp(cfg, url, method, path, body, content_type);
}

}  // namespace

bool RetryableStatus(int status) {
  switch (status) {
    case 0:    // transport failure (refused/reset/timeout/malformed)
    case 429:  // throttled — the apiserver WANTS a retry (with backoff)
    case 500:
    case 502:
    case 503:
    case 504:
      return true;
    default:
      return false;  // success, or a terminal 4xx retries cannot fix
  }
}

bool DecodeChunkedBody(const std::string& body, std::string* decoded) {
  decoded->clear();
  size_t pos = 0;
  while (pos < body.size()) {
    size_t nl = body.find("\r\n", pos);
    if (nl == std::string::npos) return false;  // size line cut off
    // strtol returns 0 for both a real "0" terminator and an unparseable
    // size line — distinguish via endptr so a corrupted chunk header is a
    // truncation error, not a silently-empty 200 body.
    char* end = nullptr;
    long chunk = strtol(body.c_str() + pos, &end, 16);
    if (end == body.c_str() + pos || chunk < 0) return false;  // garbage
    if (chunk == 0) return true;  // the terminator: complete stream
    if (nl + 2 + static_cast<size_t>(chunk) > body.size())
      return false;  // truncated chunk data
    decoded->append(body, nl + 2, static_cast<size_t>(chunk));
    pos = nl + 2 + static_cast<size_t>(chunk) + 2;
  }
  return false;  // ran out of bytes before the 0-length terminator
}

int ParseRetryAfterMs(const std::string& lowered_headers) {
  size_t pos = lowered_headers.find("retry-after:");
  if (pos == std::string::npos) return 0;
  pos += strlen("retry-after:");
  while (pos < lowered_headers.size() && lowered_headers[pos] == ' ') ++pos;
  char* end = nullptr;
  double secs = strtod(lowered_headers.c_str() + pos, &end);
  if (end == lowered_headers.c_str() + pos || secs < 0) return 0;
  if (secs > 3600) secs = 3600;  // a buggy/hostile header must not park us
  return static_cast<int>(secs * 1000);
}

bool Config::InCluster(Config* out) {
  const char* host = getenv("KUBERNETES_SERVICE_HOST");
  const char* port = getenv("KUBERNETES_SERVICE_PORT");
  if (!host || !*host) return false;
  std::string h = host;
  if (h.find(':') != std::string::npos && h[0] != '[')
    h = "[" + h + "]";  // IPv6 single-stack clusters export a bare literal
  out->base_url = "https://" + h + ":" + (port ? port : "443");
  const char* sa = "/var/run/secrets/kubernetes.io/serviceaccount";
  ReadFileTrim(std::string(sa) + "/token", &out->token);
  std::string ca = std::string(sa) + "/ca.crt";
  if (access(ca.c_str(), R_OK) == 0) {
    out->ca_file = ca;
  } else {
    // Never downgrade to unverified TLS silently — a missing projected CA
    // is a misconfiguration worth shouting about. Requests will FAIL until
    // the projection is fixed or the operand is deployed with the explicit
    // --insecure-skip-tls-verify flag (set by the caller, never here: the
    // in-cluster path is exactly where the ServiceAccount token the check
    // protects lives).
    fprintf(stderr,
            "kubeclient: WARNING: %s unreadable; https requests will fail "
            "until the CA projection is fixed (or the operand is run with "
            "--insecure-skip-tls-verify)\n", ca.c_str());
  }
  return true;
}

Response Call(const Config& cfg, const std::string& method,
              const std::string& path, const std::string& body,
              const std::string& content_type) {
  Response resp;
  for (int attempt = 1;; ++attempt) {
    resp = CallOnce(cfg, method, path, body, content_type);
    if (!RetryableStatus(resp.status) || attempt >= cfg.max_attempts)
      return resp;
    // Config refusals (no CA file for https) report status 0 like a
    // transport failure but can never succeed on retry — fail now.
    if (resp.status == 0 && resp.error.rfind("refusing", 0) == 0)
      return resp;
    int wait_ms =
        resp.retry_after_ms > 0
            ? (resp.retry_after_ms < cfg.retry_cap_ms ? resp.retry_after_ms
                                                      : cfg.retry_cap_ms)
            : WatchBackoffMs(attempt, cfg.retry_base_ms, cfg.retry_cap_ms);
    usleep(static_cast<useconds_t>(wait_ms) * 1000);
  }
}

// ------------------------------------------------------------------ watch

int ElapsedMs(const struct timespec& t0) {
  struct timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  return static_cast<int>((now.tv_sec - t0.tv_sec) * 1000 +
                          (now.tv_nsec - t0.tv_nsec) / 1000000);
}

int WatchBackoffMs(int attempt, int base_ms, int cap_ms) {
  if (base_ms < 1) base_ms = 1;
  if (cap_ms < 1) cap_ms = 1;
  if (base_ms > cap_ms) return cap_ms;
  if (attempt < 1) attempt = 1;
  long ms = base_ms;
  for (int i = 1; i < attempt && ms < cap_ms; ++i) ms *= 2;
  return static_cast<int>(ms < cap_ms ? ms : cap_ms);
}

WatchStream::~WatchStream() { Close(); }

void WatchStream::Close() {
  if (fd_ >= 0) {
    close(fd_);
    fd_ = -1;
  }
  if (pid_ > 0) {
    kill(pid_, SIGKILL);
    int st = 0;
    waitpid(pid_, &st, 0);
    pid_ = -1;
  }
  if (!hdr_file_.empty()) {
    unlink(hdr_file_.c_str());
    hdr_file_.clear();
  }
  raw_.clear();
  body_.clear();
  headers_done_ = false;
  chunked_ = false;
  saw_final_chunk_ = false;
  chunk_left_ = -1;
}

bool WatchStream::Open(const Config& cfg, const std::string& path_and_query,
                       int max_seconds, std::string* err) {
  Close();
  Url url;
  if (!ParseUrl(cfg.base_url, &url, err)) return false;
  if (url.https) {
    if (cfg.ca_file.empty() && !cfg.insecure_skip_tls_verify) {
      *err = "refusing unverified https watch: no CA file";
      return false;
    }
    // Token via a 0600 header file, never argv (same rationale as
    // CurlHttps). The file must outlive exec — curl opens it lazily — so
    // it is unlinked in Close(), not here.
    //
    // --fail: a non-2xx watch response (403 RBAC denial, 410 Gone) makes
    // curl exit without emitting the apiserver's kind:Status error body.
    // Without it those bodies stream out of this fd as "event" lines, and
    // the consumer reconciles on each one — a hot loop that bypasses
    // --interval for as long as the denial persists. With it the stream
    // just hits EOF (kClosed) and the caller falls back to generation
    // polling at its normal cadence.
    std::vector<std::string> args = {
        "curl", "-sS", "-N", "--fail", "--max-time",
        std::to_string(max_seconds),
        "-H", "Accept: application/json",
    };
    if (!cfg.user_agent.empty())
      args.insert(args.end(), {"-A", cfg.user_agent});
    if (!cfg.token.empty()) {
      char hdr_path[] = "/tmp/tpuop-watch-hdr-XXXXXX";
      int hdr_fd = mkstemp(hdr_path);
      if (hdr_fd >= 0) hdr_file_ = hdr_path;  // recorded BEFORE the write
                                              // so a failed write still
                                              // gets the file (possibly
                                              // holding a partial token)
                                              // unlinked by Close()
      std::string hdr = "Authorization: Bearer " + cfg.token + "\n";
      if (hdr_fd < 0 || write(hdr_fd, hdr.data(), hdr.size()) !=
                            static_cast<ssize_t>(hdr.size())) {
        *err = "cannot stage auth header";
        if (hdr_fd >= 0) close(hdr_fd);
        Close();
        return false;
      }
      close(hdr_fd);
      args.insert(args.end(), {"-H", std::string("@") + hdr_file_});
    }
    if (!cfg.ca_file.empty())
      args.insert(args.end(), {"--cacert", cfg.ca_file});
    else
      args.push_back("-k");
    args.push_back(cfg.base_url + path_and_query);

    int pipefd[2];
    if (pipe(pipefd) != 0) {
      *err = "pipe failed";
      return false;
    }
    pid_ = fork();
    if (pid_ < 0) {
      *err = "fork failed";
      close(pipefd[0]);
      close(pipefd[1]);
      pid_ = -1;
      return false;
    }
    if (pid_ == 0) {
      dup2(pipefd[1], 1);
      close(pipefd[0]);
      close(pipefd[1]);
      std::vector<char*> argv;
      for (auto& a : args) argv.push_back(const_cast<char*>(a.c_str()));
      argv.push_back(nullptr);
      execvp("curl", argv.data());
      _exit(127);
    }
    close(pipefd[1]);
    fd_ = pipefd[0];
    headers_done_ = true;  // curl emits the (dechunked) body only
    return true;
  }

  fd_ = ConnectTcp(url.host, url.port, cfg.timeout_ms, err);
  if (fd_ < 0) return false;
  std::string req = "GET " + url.base_path + path_and_query + " HTTP/1.1\r\n" +
                    "Host: " + url.host + "\r\n" +
                    "Connection: close\r\nAccept: application/json\r\n";
  if (!cfg.user_agent.empty())
    req += "User-Agent: " + cfg.user_agent + "\r\n";
  if (!cfg.token.empty()) req += "Authorization: Bearer " + cfg.token + "\r\n";
  req += "\r\n";
  size_t off = 0;
  while (off < req.size()) {
    ssize_t n = write(fd_, req.data() + off, req.size() - off);
    if (n <= 0) {
      *err = "write failed";
      Close();
      return false;
    }
    off += n;
  }
  return true;
}

bool WatchStream::Decode() {
  if (!headers_done_) return true;
  if (!chunked_) {
    body_ += raw_;
    raw_.clear();
    return true;
  }
  size_t pos = 0;
  while (pos < raw_.size()) {
    if (chunk_left_ > 0) {
      size_t take = std::min(static_cast<size_t>(chunk_left_),
                             raw_.size() - pos);
      body_.append(raw_, pos, take);
      pos += take;
      chunk_left_ -= take;
      continue;
    }
    // need a chunk-size line; an empty line here is the CRLF that trails
    // a completed chunk body
    size_t nl = raw_.find("\r\n", pos);
    if (nl == std::string::npos) break;
    std::string szline = raw_.substr(pos, nl - pos);
    pos = nl + 2;
    if (szline.empty()) continue;
    char* end = nullptr;
    long sz = strtol(szline.c_str(), &end, 16);
    if (end == szline.c_str() || sz < 0) return false;
    if (sz == 0) {
      saw_final_chunk_ = true;
      break;
    }
    chunk_left_ = sz;
  }
  raw_.erase(0, pos);
  return true;
}

WatchStream::Result WatchStream::Next(int wait_ms, std::string* line) {
  if (fd_ < 0) return kClosed;
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  while (true) {
    size_t nl;
    while ((nl = body_.find('\n')) != std::string::npos) {
      std::string l = body_.substr(0, nl);
      body_.erase(0, nl + 1);
      while (!l.empty() && (l.back() == '\r' || l.back() == ' '))
        l.pop_back();
      if (!l.empty()) {
        *line = l;
        return kEvent;
      }
    }
    if (saw_final_chunk_) return kClosed;
    // left clamps to 0, not an early return: Next(0) must still drain
    // data already readable on the transport (the caller's non-blocking
    // pump pattern), returning kTimeout only when poll says idle.
    int left = wait_ms - ElapsedMs(t0);
    if (left < 0) left = 0;
    struct pollfd pfd = {fd_, POLLIN, 0};
    int prc = poll(&pfd, 1, left);
    if (prc == 0) return kTimeout;
    if (prc < 0) return kError;
    char buf[8192];
    ssize_t n = read(fd_, buf, sizeof(buf));
    if (n < 0) return kError;
    if (n == 0) return kClosed;
    raw_.append(buf, n);
    if (!headers_done_) {
      size_t he = raw_.find("\r\n\r\n");
      if (he == std::string::npos) continue;
      std::string headers = raw_.substr(0, he);
      raw_.erase(0, he + 4);
      if (headers.compare(0, 5, "HTTP/") != 0) return kError;
      size_t lsp = headers.find(' ');
      size_t lend = headers.find("\r\n");
      if (lsp == std::string::npos ||
          (lend != std::string::npos && lsp > lend))
        return kError;
      if (atoi(headers.c_str() + lsp + 1) != 200) return kError;
      for (char& c : headers) c = tolower(c);
      chunked_ =
          headers.find("transfer-encoding: chunked") != std::string::npos;
      headers_done_ = true;
    }
    if (!Decode()) return kError;
  }
}

}  // namespace kubeclient
