#include "minijson.h"

#include <cmath>
#include <cstdio>
#include <cstring>

namespace minijson {

ValuePtr Value::MakeObject() {
  auto v = std::make_shared<Value>();
  v->type_ = Type::kObject;
  return v;
}

ValuePtr Value::MakeArray() {
  auto v = std::make_shared<Value>();
  v->type_ = Type::kArray;
  return v;
}

ValuePtr Value::Get(const std::string& key) const {
  if (type_ != Type::kObject) return nullptr;
  for (const auto& [k, v] : obj_)
    if (k == key) return v;
  return nullptr;
}

void Value::Set(const std::string& key, ValuePtr v) {
  for (auto& [k, existing] : obj_) {
    if (k == key) {
      existing = std::move(v);
      return;
    }
  }
  obj_.emplace_back(key, std::move(v));
}

ValuePtr Value::Path(const std::string& dotted) const {
  size_t start = 0;
  const Value* cur = this;
  ValuePtr held;
  while (start <= dotted.size()) {
    size_t dot = dotted.find('.', start);
    std::string key = dotted.substr(
        start, dot == std::string::npos ? std::string::npos : dot - start);
    held = cur->Get(key);
    if (!held) return nullptr;
    cur = held.get();
    if (dot == std::string::npos) break;
    start = dot + 1;
  }
  return held;
}

std::string Value::PathString(const std::string& dotted,
                              const std::string& fallback) const {
  ValuePtr v = Path(dotted);
  return v && v->is_string() ? v->as_string() : fallback;
}

double Value::PathNumber(const std::string& dotted, double fallback) const {
  ValuePtr v = Path(dotted);
  return v && v->is_number() ? v->as_number() : fallback;
}

namespace {

void EscapeTo(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
  out->push_back('"');
}

struct Parser {
  const char* p;
  const char* end;
  std::string err;

  void Skip() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool Fail(const char* msg) {
    char buf[96];
    snprintf(buf, sizeof(buf), "%s at byte %zd", msg,
             static_cast<ssize_t>(p - start));
    err = buf;
    return false;
  }

  bool Literal(const char* lit) {
    size_t n = strlen(lit);
    if (static_cast<size_t>(end - p) < n || strncmp(p, lit, n) != 0)
      return Fail("bad literal");
    p += n;
    return true;
  }

  bool ParseString(std::string* out) {
    if (p >= end || *p != '"') return Fail("expected string");
    ++p;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        ++p;
        if (p >= end) return Fail("truncated escape");
        switch (*p) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            if (end - p < 5) return Fail("truncated \\u");
            unsigned code = 0;
            for (int i = 1; i <= 4; ++i) {
              char c = p[i];
              code <<= 4;
              if (c >= '0' && c <= '9') code |= c - '0';
              else if (c >= 'a' && c <= 'f') code |= c - 'a' + 10;
              else if (c >= 'A' && c <= 'F') code |= c - 'A' + 10;
              else return Fail("bad \\u digit");
            }
            p += 4;
            // UTF-8 encode (surrogate pairs folded to U+FFFD — manifest
            // content is ASCII/BMP in practice)
            if (code >= 0xD800 && code <= 0xDFFF) code = 0xFFFD;
            if (code < 0x80) {
              out->push_back(static_cast<char>(code));
            } else if (code < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (code >> 6)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xE0 | (code >> 12)));
              out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            }
            break;
          }
          default:
            return Fail("bad escape");
        }
        ++p;
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) return Fail("unterminated string");
    ++p;  // closing quote
    return true;
  }

  ValuePtr ParseValue(int depth) {
    if (depth > 64) {
      Fail("nesting too deep");
      return nullptr;
    }
    Skip();
    if (p >= end) {
      Fail("unexpected end");
      return nullptr;
    }
    switch (*p) {
      case '{': {
        ++p;
        auto obj = Value::MakeObject();
        Skip();
        if (p < end && *p == '}') {
          ++p;
          return obj;
        }
        while (true) {
          Skip();
          std::string key;
          if (!ParseString(&key)) return nullptr;
          Skip();
          if (p >= end || *p != ':') {
            Fail("expected ':'");
            return nullptr;
          }
          ++p;
          ValuePtr v = ParseValue(depth + 1);
          if (!v) return nullptr;
          obj->Set(key, v);
          Skip();
          if (p < end && *p == ',') {
            ++p;
            continue;
          }
          if (p < end && *p == '}') {
            ++p;
            return obj;
          }
          Fail("expected ',' or '}'");
          return nullptr;
        }
      }
      case '[': {
        ++p;
        auto arr = Value::MakeArray();
        Skip();
        if (p < end && *p == ']') {
          ++p;
          return arr;
        }
        while (true) {
          ValuePtr v = ParseValue(depth + 1);
          if (!v) return nullptr;
          arr->Append(v);
          Skip();
          if (p < end && *p == ',') {
            ++p;
            continue;
          }
          if (p < end && *p == ']') {
            ++p;
            return arr;
          }
          Fail("expected ',' or ']'");
          return nullptr;
        }
      }
      case '"': {
        std::string s;
        if (!ParseString(&s)) return nullptr;
        return std::make_shared<Value>(s);
      }
      case 't':
        if (!Literal("true")) return nullptr;
        return std::make_shared<Value>(true);
      case 'f':
        if (!Literal("false")) return nullptr;
        return std::make_shared<Value>(false);
      case 'n':
        if (!Literal("null")) return nullptr;
        return std::make_shared<Value>();
      default: {
        // Scan per the JSON number grammar before strtod — bare strtod
        // also accepts inf/nan/hex, which must stay malformed here.
        const char* q = p;
        if (q < end && *q == '-') ++q;
        const char* int_start = q;
        while (q < end && *q >= '0' && *q <= '9') ++q;
        if (q == int_start ||
            (*int_start == '0' && q - int_start > 1)) {
          Fail("bad number");
          return nullptr;
        }
        if (q < end && *q == '.') {
          ++q;
          const char* frac_start = q;
          while (q < end && *q >= '0' && *q <= '9') ++q;
          if (q == frac_start) {
            Fail("bad number");
            return nullptr;
          }
        }
        if (q < end && (*q == 'e' || *q == 'E')) {
          ++q;
          if (q < end && (*q == '+' || *q == '-')) ++q;
          const char* exp_start = q;
          while (q < end && *q >= '0' && *q <= '9') ++q;
          if (q == exp_start) {
            Fail("bad number");
            return nullptr;
          }
        }
        double d = strtod(std::string(p, q).c_str(), nullptr);
        p = q;
        return std::make_shared<Value>(d);
      }
    }
  }

  const char* start;
};

}  // namespace

void Value::DumpTo(std::string* out) const {
  switch (type_) {
    case Type::kNull: *out += "null"; break;
    case Type::kBool: *out += bool_ ? "true" : "false"; break;
    case Type::kNumber: {
      char buf[32];
      if (num_ == std::floor(num_) && std::fabs(num_) < 1e15) {
        snprintf(buf, sizeof(buf), "%lld", static_cast<long long>(num_));
      } else {
        snprintf(buf, sizeof(buf), "%.17g", num_);
      }
      *out += buf;
      break;
    }
    case Type::kString: EscapeTo(str_, out); break;
    case Type::kArray: {
      out->push_back('[');
      for (size_t i = 0; i < arr_.size(); ++i) {
        if (i) out->push_back(',');
        arr_[i]->DumpTo(out);
      }
      out->push_back(']');
      break;
    }
    case Type::kObject: {
      out->push_back('{');
      bool first = true;
      for (const auto& [k, v] : obj_) {
        if (!first) out->push_back(',');
        first = false;
        EscapeTo(k, out);
        out->push_back(':');
        v->DumpTo(out);
      }
      out->push_back('}');
      break;
    }
  }
}

std::string Value::Dump() const {
  std::string out;
  DumpTo(&out);
  return out;
}

ValuePtr Parse(const std::string& text, std::string* err) {
  Parser parser;
  parser.p = text.data();
  parser.start = text.data();
  parser.end = text.data() + text.size();
  ValuePtr v = parser.ParseValue(0);
  if (v) {
    parser.Skip();
    if (parser.p != parser.end) {
      parser.Fail("trailing garbage");
      v = nullptr;
    }
  }
  if (!v && err) *err = parser.err;
  return v;
}

}  // namespace minijson
