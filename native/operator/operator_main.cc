// tpu-operator — the stack's controller (gpu-operator analog).
//
// The reference's `helm install --wait gpu-operator` creates a Go
// controller that rolls five operand DaemonSets onto accelerator nodes in
// dependency order, each step gated on the previous one's readiness
// (reference README.md:101-110; trace in SURVEY.md §3.3). This daemon
// reproduces that core behavior for the TPU operands:
//
//  - reads a manifest bundle from --bundle-dir (a mounted ConfigMap rendered
//    by `tpu_cluster.render.operator_bundle`): flat files named
//    "NN-stage--object.json"; lexicographic order = rollout order, the
//    "NN-stage" prefix is the readiness gate boundary;
//  - applies each stage against the apiserver via server-side apply
//    (one apply PATCH per object under the "tpu-operator" field manager,
//    kubeapi::FieldManager(); drift in our own operands is force-reverted
//    per-field), degrading to GET-then-POST/merge-PATCH — sticky per
//    process — when the apiserver predates SSA (415/400);
//  - waits for every workload object in the stage to be Ready before
//    touching the next stage (helm --wait / operator ordering analog);
//  - loops forever re-reconciling (DaemonSet deleted by hand -> recreated
//    next pass), or runs one pass with --once (the `tpuctl apply --wait`
//    backend);
//  - serves /status /healthz /metrics on --status-port while reconciling
//    (single-threaded: the status socket is pumped during readiness waits).

#include <dirent.h>
#include <netinet/in.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "../common/httpread.h"
#include "informer.h"
#include "kubeapi.h"
#include "kubeclient.h"
#include "minijson.h"
#include "workqueue.h"

namespace {

volatile sig_atomic_t g_stop = 0;
void HandleSignal(int) { g_stop = 1; }

struct Options {
  std::string apiserver;     // "" = in-cluster config
  std::string token_file;
  std::string ca_file;
  std::string bundle_dir = "/etc/tpu-operator/bundle";
  std::string policy;        // TpuStackPolicy name; "" = no policy gating
  int policy_poll_ms = 2000; // CR-change probe cadence inside the sleep
                             // (watch fallback; also the bundle-stat and
                             // watch-pump cadence)
  bool policy_watch = true;  // event-driven CR watch (?watch=1 stream);
                             // GET-probe polling remains the fallback
  bool operand_watch = true; // event-driven drift repair: per-collection
                             // informer caches + the rate-limited
                             // workqueue; the interval pass stays the
                             // full-resync backstop. --no-operand-watch
                             // = no informers at all (request-driven
                             // passes, the pre-informer behavior).
  int page_limit = 200;      // informer LIST pagination (?limit=)
  int watch_window_s = 30;   // informer watch timeoutSeconds — also the
                             // staleness bound a healthy idle stream
                             // guarantees (sync_lag_seconds source)
  int interval_s = 15;
  int stage_timeout_s = 600;
  int poll_ms = 1000;
  int status_port = 9402;    // 0 = disabled
  bool leader_elect = false; // coordination.k8s.io Lease election
  int lease_duration_s = 30;
  std::string lease_name = "tpu-operator";
  bool once = false;
  bool allow_empty_daemonsets = false;
  bool insecure_skip_tls_verify = false;
  // Chrome trace-event output (ISSUE 8): when set, the operator dumps
  // its bounded trace ring (kubeapi::TraceEmitter) here ATOMICALLY
  // (tmp + rename) after every reconcile pass and on shutdown, so a
  // crashed/SIGTERM'd operator still leaves a parseable post-mortem
  // timeline `tpuctl trace merge` can lay next to the CLI's.
  std::string trace_out;
};

// The runtime feature-flag surface (ClusterPolicy analog, reference
// README.md:101-110): bundle objects are labeled with the operand key they
// belong to, and the live TpuStackPolicy CR decides which operands run.
// Must match tpu_cluster/render/operator_bundle.py.
const char kOperandLabel[] = "tpu-stack.dev/operand";
const char kInstanceLabel[] = "tpu-stack.dev/instance";
const char kDefaultEnabledAnnotation[] = "tpu-stack.dev/default-enabled";
const char kPolicyPathPrefix[] =
    "/apis/tpu-stack.dev/v1alpha1/tpustackpolicies/";

// The tpu-stack.dev/traceparent annotation off an object (watch-event
// payloads, API response bodies); "" when absent. The key contains
// dots, so walk explicitly — no dotted-path lookup.
std::string AnnotationTraceparent(const minijson::Value& obj) {
  minijson::ValuePtr meta = obj.Get("metadata");
  minijson::ValuePtr anns = meta ? meta->Get("annotations") : nullptr;
  minijson::ValuePtr tp =
      anns ? anns->Get(kubeapi::TraceparentAnnotation()) : nullptr;
  return tp && tp->is_string() ? tp->as_string() : "";
}

struct BundleObject {
  std::string file;
  std::string stage;
  std::string operand;  // kOperandLabel value; "" = not operand-gated
  // install-time intent (kDefaultEnabledAnnotation): what gating falls
  // back to when no policy CR is available
  bool default_enabled = true;
  minijson::ValuePtr obj;
  // reconcile state (refreshed every pass)
  bool applied = false;
  bool ready = false;
  bool disabled = false;  // policy-gated off this pass
  std::string error;
  std::string uid;  // live object's metadata.uid (event correlation)
  // the tpu-stack.dev/traceparent annotation observed on the live
  // object (stamped by the tpuctl apply that last mutated it): the
  // trace id the operator's apply/reconcile slices carry so a merged
  // timeline shows WHICH rollout caused this reconcile
  std::string traceparent;
  // live object's metadata.generation as last applied/observed: the
  // drift watch's filter — a MODIFIED event with a different generation
  // is an external spec edit, an unchanged one is status churn
  double generation = 0;
};

bool LoadBundle(const std::string& dir, std::vector<BundleObject>* out,
                std::string* err) {
  DIR* d = opendir(dir.c_str());
  if (!d) {
    *err = "cannot open bundle dir " + dir;
    return false;
  }
  std::vector<std::string> names;
  while (struct dirent* e = readdir(d)) {
    std::string name = e->d_name;
    if (name.size() > 5 && name.substr(name.size() - 5) == ".json" &&
        name[0] != '.')
      names.push_back(name);
  }
  closedir(d);
  std::sort(names.begin(), names.end());
  if (names.empty()) {
    *err = "bundle dir " + dir + " contains no .json manifests";
    return false;
  }
  out->clear();
  for (const auto& name : names) {
    std::string text;
    // trailing-newline trim is harmless for JSON documents
    if (!kubeclient::ReadFileTrim(dir + "/" + name, &text)) {
      *err = "cannot read " + name;
      return false;
    }
    std::string perr;
    minijson::ValuePtr obj = minijson::Parse(text, &perr);
    if (!obj || !obj->is_object()) {
      *err = name + ": " + (perr.empty() ? "not a JSON object" : perr);
      return false;
    }
    BundleObject bo;
    bo.file = name;
    size_t sep = name.find("--");
    bo.stage = sep == std::string::npos ? name.substr(0, name.size() - 5)
                                        : name.substr(0, sep);
    bo.obj = obj;
    minijson::ValuePtr meta = obj->Get("metadata");
    minijson::ValuePtr labels = meta ? meta->Get("labels") : nullptr;
    minijson::ValuePtr operand = labels ? labels->Get(kOperandLabel) : nullptr;
    if (operand && operand->is_string()) bo.operand = operand->as_string();
    minijson::ValuePtr anns = meta ? meta->Get("annotations") : nullptr;
    minijson::ValuePtr dflt =
        anns ? anns->Get(kDefaultEnabledAnnotation) : nullptr;
    if (dflt && dflt->is_string() && dflt->as_string() == "false")
      bo.default_enabled = false;
    out->push_back(std::move(bo));
  }
  return true;
}

class StatusServer {
 public:
  bool enabled() const { return fd_ >= 0; }

  bool Listen(int port) {
    if (port <= 0) return true;
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return false;
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(fd_, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) !=
            0 ||
        listen(fd_, 8) != 0) {
      close(fd_);
      fd_ = -1;
      return false;
    }
    return true;
  }

  // Serve pending requests for up to wait_ms — doubles as the loop's sleep.
  // health_body is what /healthz answers with (the degraded-state surface:
  // consecutive-failure count + last error when unhealthy, so a flapping
  // apiserver is visible in the probe output, not silent).
  void Pump(int wait_ms, const std::string& status_json,
            const std::string& metrics, bool healthy,
            const std::string& health_body) {
    if (fd_ < 0) {
      if (wait_ms > 0) usleep(wait_ms * 1000);
      return;
    }
    struct timespec t0;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    int left = wait_ms;
    do {
      struct pollfd pfd = {fd_, POLLIN, 0};
      int rc = poll(&pfd, 1, left < 0 ? 0 : left);
      if (rc > 0) {
        int cfd = accept(fd_, nullptr, nullptr);
        if (cfd >= 0) {
          // A silent client must not wedge the single-threaded daemon:
          // bound both directions of the exchange.
          struct timeval tv = {0, 500 * 1000};
          setsockopt(cfd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
          setsockopt(cfd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
          // Read the whole request head (\r\n\r\n) — a split first line
          // would otherwise mis-parse the path (shared bounded reader,
          // native/common/httpread.h).
          char buf[2048];
          size_t have =
              httpread::ReadRequestHead(cfd, buf, sizeof(buf), &g_stop);
          std::string body = status_json, ctype = "application/json";
          int code = 200;
          if (have > 0) {
            char method[8], path[128];
            if (sscanf(buf, "%7s %127s", method, path) == 2) {
              if (strcmp(path, "/metrics") == 0) {
                body = metrics;
                ctype = "text/plain; version=0.0.4";
              } else if (strcmp(path, "/healthz") == 0) {
                body = health_body;
                ctype = "text/plain";
                code = healthy ? 200 : 503;
              }
            }
          }
          char hdr[256];
          snprintf(hdr, sizeof(hdr),
                   "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                   "Content-Length: %zu\r\nConnection: close\r\n\r\n",
                   code, code == 200 ? "OK" : "Service Unavailable",
                   ctype.c_str(), body.size());
          (void)!write(cfd, hdr, strlen(hdr));
          (void)!write(cfd, body.data(), body.size());
          close(cfd);
        }
      }
      left = wait_ms - kubeclient::ElapsedMs(t0);
    } while (left > 0 && !g_stop);
  }

 private:
  int fd_ = -1;
};

std::string NowRfc3339() {
  char buf[32];
  time_t t = time(nullptr);
  struct tm tm_utc;
  gmtime_r(&t, &tm_utc);
  strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%SZ", &tm_utc);
  return buf;
}

class Operator {
 public:
  Operator(const Options& opt, kubeclient::Config cfg)
      : opt_(opt), cfg_(std::move(cfg)) {
    char host[256] = "host";
    gethostname(host, sizeof(host) - 1);
    identity_ = std::string(host) + "-" + std::to_string(getpid());
    // sync lag is informer-cache staleness when the informer core runs
    // (see Metrics); in the request-driven modes it falls back to
    // "seconds since the last CONVERGED pass", counted from process
    // start before the first one, so a never-converging operator shows
    // an ever-growing lag instead of a flat 0
    clock_gettime(CLOCK_MONOTONIC, &start_ts_);
  }

  bool LoadOrReloadBundle() {
    // Baseline the fingerprint BEFORE reading the bundle: a re-render
    // landing mid-pass then differs from the baseline and triggers an
    // immediate next pass instead of being absorbed silently.
    pass_bundle_fp_ = BundleFingerprint();
    std::string err;
    if (!LoadBundle(opt_.bundle_dir, &bundle_, &err)) {
      fprintf(stderr, "tpu-operator: %s\n", err.c_str());
      return false;
    }
    return true;
  }

  bool Listen() { return status_.Listen(opt_.status_port); }

  // One full reconcile pass: fetch the policy, apply + gate stage by stage,
  // report back through the CR's status subresource. Returns true when
  // every enabled object applied and became ready. Maintains the
  // degraded-state counters /healthz and /metrics surface: consecutive
  // failed passes and the first error of the latest failed one.
  bool ReconcilePass() {
    struct timespec t0;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    double trace_ts = trace_.NowUs();
    bool ok = ReconcileObjects();
    if (ok) {
      consecutive_failures_ = 0;
      last_error_.clear();
    } else {
      ++consecutive_failures_;
      last_error_ = FirstError();
    }
    WritePolicyStatus(ok);
    // telemetry (ISSUE 6): the pass duration feeds the fixed-bucket
    // reconcile histogram (including the status write-back — the
    // whole pass is what the interval budget buys), and a CONVERGED
    // pass resets the sync-lag clock the /metrics gauge reads.
    ObserveReconcileSeconds(kubeclient::ElapsedMs(t0) / 1000.0);
    if (ok) {
      clock_gettime(CLOCK_MONOTONIC, &last_sync_);
      synced_ = true;
    }
    // one slice per pass + an atomic dump: a SIGKILL between passes
    // still leaves the last pass's complete timeline on disk
    trace_.AddComplete("reconcile-pass", "reconcile", trace_ts,
                       trace_.NowUs() - trace_ts,
                       {{"pass", std::to_string(passes_)},
                        {"ok", ok ? "true" : "false"}});
    DumpTrace();
    return ok;
  }

  // The first per-object error of the pass that just failed — the triage
  // line /healthz carries (a pass interrupted by SIGTERM has none).
  std::string FirstError() const {
    for (const auto& bo : bundle_)
      if (!bo.error.empty()) return bo.file + ": " + bo.error;
    return "pass interrupted";
  }

  bool ReconcileObjects() {
    ++passes_;
    EnsureInformers();
    if (ShouldFetchPolicy()) FetchPolicy();
    RebuildKeyIndex();
    for (auto& bo : bundle_) {
      bo.applied = false;
      bo.ready = false;
      bo.disabled = false;
      bo.error.clear();
    }
    size_t i = 0;
    while (i < bundle_.size() && !g_stop) {
      const std::string stage = bundle_[i].stage;
      size_t stage_end = i;
      while (stage_end < bundle_.size() && bundle_[stage_end].stage == stage)
        ++stage_end;
      // apply every enabled object of the stage; a policy-disabled
      // operand's live objects are deleted instead (helm switch-flip
      // analog — `--set metricsExporter.enabled=false` rolls the operand
      // out of the cluster, reference README.md:104-110)
      for (size_t j = i; j < stage_end; ++j) {
        if (!OperandEnabled(bundle_[j].operand,
                            bundle_[j].default_enabled)) {
          if (!DeleteDisabled(&bundle_[j])) {
            fprintf(stderr,
                    "tpu-operator: stage %s: delete disabled %s failed: %s\n",
                    stage.c_str(), bundle_[j].file.c_str(),
                    bundle_[j].error.c_str());
            EmitEvent("DeleteFailed",
                      "stage " + stage + ": " + bundle_[j].error,
                      bundle_[j]);
            return false;
          }
          continue;
        }
        // Informer fast path: when the cached live object already
        // matches the desired manifest field-for-field, the resync
        // round costs ZERO requests for it — the informer cache, not a
        // GET, is the drift probe. Identity (uid/generation/
        // traceparent) is adopted from the cache like RememberUid
        // adopts it from an API response.
        if (CleanInCache(&bundle_[j])) {
          bundle_[j].applied = true;
          continue;
        }
        double apply_ts = trace_.NowUs();
        bool apply_ok = ApplyObject(&bundle_[j]);
        kubeapi::TraceEmitter::Args apply_args = {
            {"object", bundle_[j].file},
            {"ok", apply_ok ? "true" : "false"}};
        if (!bundle_[j].traceparent.empty()) {
          // the annotation tpuctl stamped on the live object: this
          // slice now names the rollout that caused the state we are
          // reconciling (the merged-timeline correlation pin)
          apply_args.push_back({"traceparent", bundle_[j].traceparent});
          apply_args.push_back(
              {"trace_id",
               kubeapi::ParseTraceparent(bundle_[j].traceparent).first});
        }
        trace_.AddComplete("apply-object", "reconcile", apply_ts,
                           trace_.NowUs() - apply_ts, apply_args);
        if (!apply_ok) {
          fprintf(stderr, "tpu-operator: stage %s: apply %s failed: %s\n",
                  stage.c_str(), bundle_[j].file.c_str(),
                  bundle_[j].error.c_str());
          EmitEvent("ApplyFailed",
                    "stage " + stage + ": " + bundle_[j].error,
                    bundle_[j]);
          return false;
        }
      }
      // gate on readiness of the stage's workload objects (helm --wait
      // analog, reference README.md:101); disabled objects don't gate
      time_t deadline = time(nullptr) + opt_.stage_timeout_s;
      double gate_ts = trace_.NowUs();
      auto gate_slice = [&](bool gate_ok) {
        trace_.AddComplete("ready-wait", "reconcile", gate_ts,
                           trace_.NowUs() - gate_ts,
                           {{"stage", stage},
                            {"ok", gate_ok ? "true" : "false"}});
      };
      while (!g_stop) {
        // The informer streams stay open THROUGH the gate (the
        // pass->watch blind window is gone): readiness comes off the
        // cache, and drift landing mid-reconcile is classified into the
        // workqueue and repaired here, not discovered by a catch-up
        // LIST later.
        PumpInformers();
        DrainQueue(16);
        bool all_ready = true;
        for (size_t j = i; j < stage_end; ++j) {
          if (bundle_[j].disabled) continue;
          if (!bundle_[j].ready && !CheckReadyAny(&bundle_[j]))
            all_ready = false;
        }
        if (all_ready) {
          gate_slice(true);
          break;
        }
        if (time(nullptr) >= deadline) {
          for (size_t j = i; j < stage_end; ++j) {
            if (!bundle_[j].ready && !bundle_[j].disabled) {
              fprintf(stderr,
                      "tpu-operator: stage %s: %s not ready after %ds\n",
                      stage.c_str(), bundle_[j].file.c_str(),
                      opt_.stage_timeout_s);
              bundle_[j].error = "not ready after " +
                                 std::to_string(opt_.stage_timeout_s) + "s";
              EmitEvent("StageTimeout",
                        "stage " + stage + ": not ready after " +
                            std::to_string(opt_.stage_timeout_s) + "s",
                        bundle_[j]);
            }
          }
          gate_slice(false);
          return false;
        }
        Sleep(opt_.poll_ms);
      }
      i = stage_end;
    }
    if (!g_stop) PruneStaleOperandObjects();
    return !g_stop;
  }

  // Garbage-collect operand objects a re-rendered bundle no longer
  // contains. The operand label marks exactly the bundle-managed set, so a
  // labeled live object absent from the bundle was dropped by an upgrade —
  // without this sweep it would leak forever (apply/patch only ever adds).
  // Runs only after a fully-converged pass; policy-disabled objects are
  // still IN the bundle, so the policy gate (not this sweep) owns them.
  void PruneStaleOperandObjects() {
    // Stale objects can only appear when the bundle's content changed:
    // sweep on the first converged pass and after any bundle change, not
    // on every steady-state pass (12 LISTs/pass across a fleet is pure
    // apiserver load otherwise).
    if (!last_pruned_fp_.empty() && last_pruned_fp_ == pass_bundle_fp_)
      return;
    bool all_ok = true;
    std::string ns, err;
    std::set<std::string> keep;
    for (const auto& bo : bundle_) {
      if (ns.empty()) ns = bo.obj->PathString("metadata.namespace");
      std::string coll = kubeapi::CollectionPath(*bo.obj, &err);
      if (!coll.empty())
        keep.insert(coll + "/" + bo.obj->PathString("metadata.name"));
    }
    // The list stays broad (operand label only) but deletion is scoped
    // to THIS install client-side: cluster-scoped collections
    // (ClusterRoles etc.) are listed cluster-wide, and deleting on the
    // operand label alone would garbage-collect a second tpu-stack
    // install's objects. An object whose instance label (stamped by the
    // bundle renderer, value = install namespace) names ANOTHER install
    // is skipped; one with NO instance label is a pre-instance-label
    // legacy object this sweep must still be able to prune — a
    // selector-side requirement would orphan those forever (dropped
    // objects are never re-applied, so they never gain the label).
    for (const auto& coll : kubeapi::SweepCollections(ns)) {
      kubeclient::Response list = kubeclient::Call(
          cfg_, "GET", coll + "?labelSelector=" + kOperandLabel);
      if (!list.ok()) continue;  // 404: nothing of this kind exists
      minijson::ValuePtr doc = minijson::Parse(list.body);
      minijson::ValuePtr items = doc ? doc->Get("items") : nullptr;
      if (!items || !items->is_array()) continue;
      for (const auto& item : items->elements()) {
        std::string name = item->PathString("metadata.name");
        if (name.empty() || keep.count(coll + "/" + name)) continue;
        // label key contains dots — walk explicitly, no dotted path
        minijson::ValuePtr imeta = item->Get("metadata");
        minijson::ValuePtr ilabels = imeta ? imeta->Get("labels") : nullptr;
        minijson::ValuePtr inst =
            ilabels ? ilabels->Get(kInstanceLabel) : nullptr;
        if (inst && inst->is_string() && inst->as_string() != ns) continue;
        kubeclient::Response del =
            kubeclient::Call(cfg_, "DELETE", coll + "/" + name);
        bool deleted = del.ok() || del.status == 404;
        if (!deleted) all_ok = false;
        fprintf(stderr,
                "tpu-operator: pruned stale operand object %s/%s (no "
                "longer in bundle)%s\n", coll.c_str(), name.c_str(),
                deleted ? "" : " [delete failed]");
      }
    }
    // a failed delete keeps the sweep armed for the next pass
    if (all_ok) last_pruned_fp_ = pass_bundle_fp_;
  }

  // ---- Leader election (coordination.k8s.io/v1 Lease) ----------------
  // Upstream gpu-operator ships controller-runtime leader election; two
  // replicas of tpu-operator without it would fight (duplicate Events,
  // racing PATCHes, double GC-prune). The standby loops on the lease and
  // reconciles NOTHING until the holder's lease expires.

  std::string InstallNamespace() const {
    for (const auto& bo : bundle_) {
      std::string ns = bo.obj->PathString("metadata.namespace");
      if (!ns.empty()) return ns;
    }
    return "default";
  }

  static std::string NowRfc3339Micro() {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    struct tm tm;
    gmtime_r(&ts.tv_sec, &tm);
    char buf[64];
    snprintf(buf, sizeof(buf), "%04d-%02d-%02dT%02d:%02d:%02d.%06ldZ",
             tm.tm_year + 1900, tm.tm_mon + 1, tm.tm_mday, tm.tm_hour,
             tm.tm_min, tm.tm_sec, ts.tv_nsec / 1000);
    return buf;
  }

  static time_t ParseRfc3339(const std::string& s) {
    struct tm tm = {};
    int y, mo, d, h, mi, se;
    if (sscanf(s.c_str(), "%d-%d-%dT%d:%d:%d", &y, &mo, &d, &h, &mi, &se)
        != 6)
      return 0;
    tm.tm_year = y - 1900;
    tm.tm_mon = mo - 1;
    tm.tm_mday = d;
    tm.tm_hour = h;
    tm.tm_min = mi;
    tm.tm_sec = se;
    return timegm(&tm);
  }

  std::string LeaseCollection() const {
    return "/apis/coordination.k8s.io/v1/namespaces/" + InstallNamespace() +
           "/leases";
  }

  // Returns whether this instance holds the lease after the call. Safe on
  // real apiservers: updates go through PUT of the GET'd object (carrying
  // its resourceVersion), so a racing standby loses with a 409 instead of
  // silently co-leading. Sets lease_error_ when the lease state could not
  // be determined or written for NON-contention reasons (RBAC denial,
  // missing namespace, unreachable apiserver) — callers surface that as
  // unhealthy instead of a silent forever-standby.
  //
  // Expiry is judged by the LOCAL observation clock, never the holder's
  // wall-clock renewTime (client-go leaderelection semantics): a takeover
  // happens only after THIS instance has watched the lease stay unchanged
  // for a full leaseDurationSeconds. Inter-node clock skew therefore
  // cannot make a standby steal a live lease. Consequence: a fresh
  // --once run cannot take over a crashed holder's lease (it has no
  // observation history) — looping instances can, which is what replicas
  // do in production.
  bool TryAcquireLease() {
    std::string path = LeaseCollection() + "/" + opt_.lease_name;
    kubeclient::Response r = kubeclient::Call(cfg_, "GET", path);
    time_t now = time(nullptr);
    if (r.status == 404) {
      std::string body =
          "{\"apiVersion\": \"coordination.k8s.io/v1\", \"kind\": \"Lease\","
          " \"metadata\": {\"name\": \"" + opt_.lease_name +
          "\", \"namespace\": \"" + InstallNamespace() + "\"},"
          " \"spec\": {\"holderIdentity\": \"" + identity_ +
          "\", \"leaseDurationSeconds\": " +
          std::to_string(opt_.lease_duration_s) +
          ", \"acquireTime\": \"" + NowRfc3339Micro() +
          "\", \"renewTime\": \"" + NowRfc3339Micro() +
          "\", \"leaseTransitions\": 0}}";
      kubeclient::Response c =
          kubeclient::Call(cfg_, "POST", LeaseCollection(), body);
      if (c.ok()) {
        lease_error_ = false;
        SetLeader(true, "acquired (new lease)");
        last_renew_ = now;
      } else if (c.status == 409) {
        lease_error_ = false;
        SetLeader(false, "lost create race");
      } else {
        // 403 = missing coordination.k8s.io RBAC; 404 = the install
        // namespace does not exist yet (in-cluster it always does — the
        // operator pod runs inside it; an external `tpu-operator
        // --leader-elect` against a fresh cluster must create it first,
        // e.g. via `tpuctl apply`). Either way this is a configuration
        // failure, not contention: say so and report unhealthy rather
        // than spinning as a silent healthy standby.
        lease_error_ = true;
        fprintf(stderr,
                "tpu-operator: LEASE CREATE FAILED (HTTP %d%s): check "
                "coordination.k8s.io/leases RBAC and that namespace %s "
                "exists; refusing to reconcile without the lease\n",
                c.status, c.status == 0 ? " transport" : "",
                InstallNamespace().c_str());
        SetLeader(false, "lease create failed (config error)");
      }
      return leader_;
    }
    if (!r.ok()) {
      // Transport trouble: keep acting as leader only inside the lease we
      // already hold (another instance cannot have taken it before our
      // renewTime + duration passes); past that, step down.
      lease_error_ = true;
      if (leader_ && now - last_renew_ < opt_.lease_duration_s) return true;
      SetLeader(false, "apiserver unreachable, lease unverifiable");
      return false;
    }
    lease_error_ = false;
    minijson::ValuePtr doc = minijson::Parse(r.body);
    minijson::ValuePtr spec = doc ? doc->Get("spec") : nullptr;
    std::string holder =
        spec && spec->Get("holderIdentity")
            ? spec->Get("holderIdentity")->as_string() : "";
    std::string renew_str = spec && spec->Get("renewTime")
                                ? spec->Get("renewTime")->as_string() : "";
    double dur = opt_.lease_duration_s;
    if (spec && spec->Get("leaseDurationSeconds"))
      dur = spec->Get("leaseDurationSeconds")->as_number();
    bool mine = holder == identity_;
    bool expired;
    if (holder.empty()) {
      expired = true;  // gracefully released
    } else if (!mine) {
      // Local observation clock: (re)start the expiry timer whenever the
      // lease CHANGES under us; only a lease we have seen frozen for a
      // full duration is dead. Never compare the holder's wall-clock
      // renewTime against ours.
      std::string key = holder + "|" + renew_str;
      if (key != observed_lease_) {
        observed_lease_ = key;
        observed_at_ = now;
      }
      expired = now - observed_at_ > static_cast<time_t>(dur);
    } else {
      expired = true;  // our own lease: renew regardless
    }
    if (!mine && !expired) {
      SetLeader(false, ("standby; lease held by " + holder).c_str());
      return false;
    }
    if (!spec) return leader_;  // malformed lease: keep current role
    spec->Set("holderIdentity",
              std::make_shared<minijson::Value>(identity_));
    spec->Set("renewTime",
              std::make_shared<minijson::Value>(NowRfc3339Micro()));
    spec->Set("leaseDurationSeconds",
              std::make_shared<minijson::Value>(
                  static_cast<double>(opt_.lease_duration_s)));
    if (!mine) {
      spec->Set("acquireTime",
                std::make_shared<minijson::Value>(NowRfc3339Micro()));
      double transitions =
          spec->Get("leaseTransitions")
              ? spec->Get("leaseTransitions")->as_number() : 0;
      spec->Set("leaseTransitions",
                std::make_shared<minijson::Value>(transitions + 1));
    }
    kubeclient::Response u = kubeclient::Call(cfg_, "PUT", path,
                                              doc->Dump());
    if (u.ok()) {
      if (!mine)
        SetLeader(true, ("took over expired lease from " +
                         (holder.empty() ? "<none>" : holder)).c_str());
      else if (!leader_)
        SetLeader(true, "re-acquired own lease");
      leader_ = true;
      last_renew_ = now;
    } else if (leader_ && now - last_renew_ >= opt_.lease_duration_s) {
      SetLeader(false, "renew failed past lease duration");
    } else if (!mine) {
      SetLeader(false, "lost takeover race");
    }
    return leader_;
  }

  bool lease_error() const { return lease_error_; }

  // Graceful release on clean shutdown (controller-runtime's
  // ReleaseOnCancel analog): an empty holderIdentity lets the next
  // instance take over immediately instead of waiting out the lease.
  // A crashed leader never gets here — that is what expiry is for.
  void ReleaseLease() {
    if (!opt_.leader_elect || !leader_) return;
    std::string path = LeaseCollection() + "/" + opt_.lease_name;
    kubeclient::Response r = kubeclient::Call(cfg_, "GET", path);
    if (!r.ok()) return;
    minijson::ValuePtr doc = minijson::Parse(r.body);
    minijson::ValuePtr spec = doc ? doc->Get("spec") : nullptr;
    if (!spec || !spec->Get("holderIdentity") ||
        spec->Get("holderIdentity")->as_string() != identity_)
      return;  // not ours anymore; nothing to release
    spec->Set("holderIdentity", std::make_shared<minijson::Value>(
                                    std::string("")));
    spec->Set("renewTime",
              std::make_shared<minijson::Value>(NowRfc3339Micro()));
    if (kubeclient::Call(cfg_, "PUT", path, doc->Dump()).ok())
      fprintf(stderr, "tpu-operator: released lease on shutdown\n");
    leader_ = false;
  }

  void SetLeader(bool lead, const char* why) {
    if (lead != leader_)
      fprintf(stderr, "tpu-operator: leader-election [%s]: %s -> %s\n",
              identity_.c_str(), why, lead ? "LEADER" : "standby");
    leader_ = lead;
  }

  bool leader() const { return leader_; }

  void RunForever() {
    while (!g_stop) {
      if (opt_.leader_elect && !TryAcquireLease()) {
        // Standby is inert: no bundle reload, no reconcile, no Events —
        // it only watches the lease. Watching a healthy holder IS its
        // job; failing to even determine the lease state (RBAC, missing
        // namespace, transport) is not, and must page someone.
        healthy_ = !lease_error_;
        SleepWatchingInputs(
            std::max(1000, opt_.lease_duration_s * 1000 / 3));
        continue;
      }
      // The bundle is a mounted ConfigMap that kubelet live-updates; reload
      // each pass so a re-rendered bundle rolls out without a pod restart
      // (a stale snapshot would merge-PATCH the upgrade away as "drift").
      std::vector<BundleObject> fresh;
      std::string err;
      pass_bundle_fp_ = BundleFingerprint();  // before the read, see
                                              // LoadOrReloadBundle
      if (LoadBundle(opt_.bundle_dir, &fresh, &err)) {
        bundle_ = std::move(fresh);
      } else {
        fprintf(stderr, "tpu-operator: bundle reload failed (%s); "
                "keeping previous bundle\n", err.c_str());
      }
      bool ok = ReconcilePass();
      healthy_ = ok;
      if (ok)
        fprintf(stderr, "tpu-operator: pass %d converged\n", passes_);
      // Failed passes back off exponentially with +/-10% jitter: an
      // apiserver bounce must not be met with a synchronized full-rate
      // retry storm from every operator in the fleet. The 5-min cap only
      // bounds the BACKOFF — a configured interval above it is honored.
      // (consecutive_failures_ is the same counter /healthz surfaces.)
      int sleep_ms = opt_.interval_s * 1000;
      if (consecutive_failures_ > 0) {
        int cap_ms = std::max(300 * 1000, sleep_ms);
        for (int i = 0; i < consecutive_failures_ && sleep_ms < cap_ms; ++i)
          sleep_ms *= 2;
        sleep_ms = std::min(sleep_ms, cap_ms);
      }
      sleep_ms = static_cast<int>(
          sleep_ms * (0.9 + 0.2 * (rand() / double(RAND_MAX))));
      // A leader must renew well inside the lease duration, whatever the
      // reconcile interval says.
      if (opt_.leader_elect)
        sleep_ms = std::min(sleep_ms,
                            std::max(1000, opt_.lease_duration_s * 1000 / 3));
      SleepWatchingInputs(sleep_ms);
    }
  }

  // Fingerprint of the bundle dir (names + sizes + mtimes): kubelet
  // rewrites the mounted ConfigMap atomically, so any re-render moves it.
  std::string BundleFingerprint() const {
    DIR* d = opendir(opt_.bundle_dir.c_str());
    if (!d) return "";
    std::vector<std::string> parts;
    struct dirent* ent;
    while ((ent = readdir(d)) != nullptr) {
      std::string name = ent->d_name;
      if (name == "." || name == "..") continue;
      struct stat st;
      std::string full = opt_.bundle_dir + "/" + name;
      if (stat(full.c_str(), &st) != 0) continue;
      parts.push_back(name + ":" + std::to_string(st.st_size) + ":" +
                      std::to_string(st.st_mtime));
    }
    closedir(d);
    std::sort(parts.begin(), parts.end());
    std::string out;
    for (const auto& p : parts) out += p + "\n";
    return out;
  }

  // --- Informer/workqueue core (controller-runtime model) ---------------
  //
  // One informer per distinct bundle collection keeps a full local cache
  // fed by paginated-LIST-once-then-WATCH, so reconcile cost is O(events)
  // instead of O(objects x passes): a synced idle operator issues ZERO
  // reads per interval (resync rounds diff the desired bundle against the
  // cache), and drift events are classified into a rate-limited dedup
  // workqueue whose Reconcile(key) repairs exactly the drifted object in
  // O(1) requests. The informer streams stay open THROUGH reconcile
  // passes, which is what deleted the old pass->watch blind-window
  // catch-up LIST: an event landing mid-reconcile sits in the queue (or
  // is re-queued by Done() if its key was being processed) instead of
  // going invisible until the interval resync.

  bool UseInformers() const { return opt_.operand_watch && !opt_.once; }

  // Distinct collection paths over ALL bundle objects — config kinds too:
  // a ConfigMap edit is drift exactly like a DaemonSet edit, and the
  // zero-idle-reads contract needs every owned kind cache-resident.
  std::vector<std::string> BundleCollections() const {
    std::vector<std::string> colls;
    for (const auto& bo : bundle_) {
      std::string err;
      std::string coll = kubeapi::CollectionPath(*bo.obj, &err);
      if (coll.empty()) continue;
      if (std::find(colls.begin(), colls.end(), coll) == colls.end())
        colls.push_back(coll);
    }
    return colls;
  }

  // Create informers for collections the bundle gained, drop informers
  // for collections it lost, and (re)try the initial paginated LIST of
  // any not yet synced (an unreachable apiserver keeps the informer
  // around unsynced; the per-object request path covers that pass).
  void EnsureInformers() {
    if (!UseInformers()) {
      informers_.clear();
      return;
    }
    std::vector<std::string> colls = BundleCollections();
    for (auto it = informers_.begin(); it != informers_.end();) {
      if (std::find(colls.begin(), colls.end(), it->first) == colls.end())
        it = informers_.erase(it);
      else
        ++it;
    }
    for (const auto& coll : colls) {
      auto& inf = informers_[coll];
      if (!inf)
        inf = std::make_unique<informer::Informer>(
            &cfg_, coll, opt_.page_limit, opt_.watch_window_s);
      if (!inf->synced()) {
        std::string err;
        if (inf->Resync(&err))
          fprintf(stderr,
                  "tpu-operator: informer %s synced (%zu objects, "
                  "%d pages)\n",
                  coll.c_str(), inf->objects().size(),
                  inf->pages_last_list());
        else
          fprintf(stderr,
                  "tpu-operator: informer %s initial list failed (%s); "
                  "pass falls back to per-object requests\n",
                  coll.c_str(), err.c_str());
      }
    }
  }

  // The synced informer covering this object, or nullptr (no informer
  // core, unknown collection, initial list still failing).
  informer::Informer* InformerFor(const BundleObject& bo) {
    if (informers_.empty()) return nullptr;
    std::string err;
    std::string coll = kubeapi::CollectionPath(*bo.obj, &err);
    auto it = informers_.find(coll);
    if (it == informers_.end() || !it->second->synced()) return nullptr;
    return it->second.get();
  }

  // coll/name -> bundle_ index; rebuilt at pass start (the bundle is
  // reloaded from disk each pass) so event classification and
  // Reconcile(key) resolve against the CURRENT desired state.
  void RebuildKeyIndex() {
    key_index_.clear();
    for (size_t i = 0; i < bundle_.size(); ++i) {
      std::string err;
      std::string coll = kubeapi::CollectionPath(*bundle_[i].obj, &err);
      if (coll.empty()) continue;
      key_index_[coll + "/" +
                 bundle_[i].obj->PathString("metadata.name")] = i;
    }
  }

  // Adopt identity from a CACHED live object — uid (event correlation),
  // generation (the drift filter), traceparent — exactly what RememberUid
  // adopts from an API response body.
  void RememberLive(BundleObject* bo, const minijson::Value& live) {
    std::string uid = live.PathString("metadata.uid");
    if (!uid.empty()) bo->uid = uid;
    double gen = live.PathNumber("metadata.generation", 0);
    if (gen > 0) bo->generation = gen;
    std::string tp = AnnotationTraceparent(live);
    if (!tp.empty()) bo->traceparent = tp;
  }

  // Zero-request convergence probe: the cached live object carries every
  // field the desired manifest specifies (SubsetMatch — server-set fields
  // the manifest doesn't mention never count as drift, the merge-patch
  // reading). True = nothing to apply; identity adopted from the cache.
  bool CleanInCache(BundleObject* bo) {
    informer::Informer* inf = InformerFor(*bo);
    if (!inf) return false;
    minijson::ValuePtr live =
        inf->GetObject(bo->obj->PathString("metadata.name"));
    if (!live) return false;
    if (!informer::SubsetMatch(*bo->obj, *live)) return false;
    RememberLive(bo, *live);
    return true;
  }

  // Readiness off the informer cache when one covers the object (zero
  // requests); one GET otherwise (CheckReady, the pre-informer path).
  bool CheckReadyAny(BundleObject* bo) {
    informer::Informer* inf = InformerFor(*bo);
    if (!inf) return CheckReady(bo);
    std::string kind = bo->obj->PathString("kind");
    if (kind != "DaemonSet" && kind != "Deployment" && kind != "Job") {
      bo->ready = true;
      return true;
    }
    minijson::ValuePtr live =
        inf->GetObject(bo->obj->PathString("metadata.name"));
    if (!live) return false;
    double gen = live->PathNumber("metadata.generation", 0);
    if (gen > 0) bo->generation = gen;
    bool ready = kubeapi::IsReady(*live);
    if (!ready && opt_.allow_empty_daemonsets && kind == "DaemonSet" &&
        live->PathNumber("status.desiredNumberScheduled", -1) == 0)
      ready = true;  // cluster has no matching nodes yet; don't wedge
    bo->ready = ready;
    return ready;
  }

  // Classify one watch event against the desired state; drifted keys go
  // into the workqueue (dedup'd while queued). The operator's own writes
  // self-filter: generation-tracked kinds compare metadata.generation
  // against the recorded applied generation (status churn echoes as
  // MODIFIED with an unchanged generation), config kinds SubsetMatch the
  // event object against the manifest.
  void OnInformerEvent(const std::string& coll, const informer::Event& ev) {
    auto it = key_index_.find(coll + "/" + ev.name);
    if (it == key_index_.end()) return;  // not an object we own
    BundleObject& bo = bundle_[it->second];
    if (bo.disabled) return;  // DeleteDisabled's own DELETED echo
    if (ev.type == "DELETED") {
      fprintf(stderr,
              "tpu-operator: operand drift (%s deleted, watch "
              "event); reconciling now\n", ev.name.c_str());
      trace_.AddInstant("drift-event", "watch",
                        {{"object", ev.name}, {"via", "operand-watch"}});
      bo.applied = false;
      bo.ready = false;
      queue_.Add(it->first);
      return;
    }
    if (!ev.object) return;
    const auto& watch_kinds = kubeapi::OperandWorkloadKinds();
    std::string kind = bo.obj->PathString("kind");
    if (std::find(watch_kinds.begin(), watch_kinds.end(), kind) !=
        watch_kinds.end()) {
      double gen = ev.object->PathNumber("metadata.generation", 0);
      // Generation filter: status churn (readiness counts) echoes as
      // MODIFIED with an unchanged generation — only an external spec
      // edit moves it. generation==0 recorded = never observed: nothing
      // to compare (the resync round's SubsetMatch still covers it).
      if (bo.generation == 0 || gen == bo.generation) return;
      fprintf(stderr,
              "tpu-operator: operand drift (%s generation "
              "%.0f -> %.0f, watch event); reconciling now\n",
              ev.name.c_str(), bo.generation, gen);
      kubeapi::TraceEmitter::Args dargs = {
          {"object", ev.name}, {"via", "operand-watch"}};
      std::string tp = AnnotationTraceparent(*ev.object);
      if (!tp.empty()) {
        // the spec edit's OWN trace context (a tpuctl re-apply): the
        // repair attributes straight back to its cause
        dargs.push_back({"traceparent", tp});
        dargs.push_back(
            {"trace_id", kubeapi::ParseTraceparent(tp).first});
      }
      trace_.AddInstant("drift-event", "watch", dargs);
      queue_.Add(it->first);
      return;
    }
    // config kind (no generation tracking): diff against desired
    if (informer::SubsetMatch(*bo.obj, *ev.object)) return;
    fprintf(stderr,
            "tpu-operator: operand drift (%s modified, watch event); "
            "reconciling now\n", ev.name.c_str());
    trace_.AddInstant("drift-event", "watch",
                      {{"object", ev.name}, {"via", "operand-watch"}});
    queue_.Add(it->first);
  }

  // Drain pending watch events from every informer, non-blocking.
  // Returns the number of events delivered.
  int PumpInformers() {
    if (informers_.empty()) return 0;
    int total = 0;
    for (auto& kv : informers_) {
      const std::string& coll = kv.first;
      int n = kv.second->Pump(
          [&](const informer::Event& ev) { OnInformerEvent(coll, ev); });
      total += n;
      // a flooding collection must not starve the status listener: the
      // informer's own drain is bounded at 64, pump /healthz between
      if (n >= 64) Sleep(0);
    }
    return total;
  }

  // Per-object reconcile — the workqueue's unit of work, the O(1)-repair
  // path. Wrapped in a "reconcile-object" trace slice carrying the
  // causing traceparent (the per-event analog of "reconcile-pass").
  bool ReconcileKey(const std::string& key) {
    auto it = key_index_.find(key);
    if (it == key_index_.end()) return true;  // bundle moved on: drop
    BundleObject& bo = bundle_[it->second];
    double ts = trace_.NowUs();
    bool ok;
    if (!OperandEnabled(bo.operand, bo.default_enabled)) {
      ok = DeleteDisabled(&bo);
    } else if (CleanInCache(&bo)) {
      bo.applied = true;  // cache already matches: drift self-resolved
      ok = true;
    } else {
      double apply_ts = trace_.NowUs();
      ok = ApplyObject(&bo);
      kubeapi::TraceEmitter::Args apply_args = {
          {"object", bo.file}, {"ok", ok ? "true" : "false"}};
      if (!bo.traceparent.empty()) {
        apply_args.push_back({"traceparent", bo.traceparent});
        apply_args.push_back(
            {"trace_id", kubeapi::ParseTraceparent(bo.traceparent).first});
      }
      trace_.AddComplete("apply-object", "reconcile", apply_ts,
                         trace_.NowUs() - apply_ts, apply_args);
    }
    kubeapi::TraceEmitter::Args args = {
        {"object", bo.file}, {"key", key},
        {"ok", ok ? "true" : "false"}};
    if (!bo.traceparent.empty()) {
      args.push_back({"traceparent", bo.traceparent});
      args.push_back(
          {"trace_id", kubeapi::ParseTraceparent(bo.traceparent).first});
    }
    trace_.AddComplete("reconcile-object", "reconcile", ts,
                       trace_.NowUs() - ts, args);
    if (ok && !bo.disabled && !bo.ready && !CheckReadyAny(&bo)) {
      // Readiness follow-up without blocking the queue: re-check at the
      // poll cadence (off the cache) until stage_timeout_s gives up —
      // the interval resync remains the backstop after that.
      time_t now = time(nullptr);
      auto d = ready_deadline_.find(key);
      if (d == ready_deadline_.end()) {
        ready_deadline_[key] = now + opt_.stage_timeout_s;
        queue_.AddAfter(key, opt_.poll_ms);
      } else if (now < d->second) {
        queue_.AddAfter(key, opt_.poll_ms);
      } else {
        fprintf(stderr,
                "tpu-operator: %s not ready after %ds (event repair); "
                "interval resync will retry\n",
                bo.file.c_str(), opt_.stage_timeout_s);
        ready_deadline_.erase(d);
      }
    } else {
      ready_deadline_.erase(key);
    }
    return ok;
  }

  // A repair must apply the FRESHEST render: the bundle is a mounted
  // ConfigMap kubelet live-updates, and repairing drift from a snapshot
  // taken before a re-render would merge the upgrade away. So before
  // working the queue, re-read the bundle if its fingerprint moved since
  // the pass baselined it. A render that fails to parse keeps the
  // previous bundle and says so loudly — the same keep-last-good
  // contract as the pass-start reload. pass_bundle_fp_ is deliberately
  // NOT advanced on success: the sleep's changed-fingerprint check must
  // still cut the interval short for the full pass (prune, stage gates)
  // that a per-key repair cannot provide.
  void RefreshBundleForRepair() {
    std::string fp = BundleFingerprint();
    if (fp.empty() || fp == pass_bundle_fp_ || fp == repair_bundle_fp_)
      return;
    repair_bundle_fp_ = fp;  // one attempt per distinct render
    std::vector<BundleObject> fresh;
    std::string err;
    if (LoadBundle(opt_.bundle_dir, &fresh, &err)) {
      bundle_ = std::move(fresh);
      RebuildKeyIndex();
    } else {
      fprintf(stderr, "tpu-operator: bundle reload failed (%s); "
              "keeping previous bundle\n", err.c_str());
    }
  }

  // Work off up to max_keys queued reconciles. Failure re-queues with
  // capped exponential backoff (AddRateLimited); success Forget()s the
  // key's strikes. A shed (depth bound hit) flags a full resync owed.
  int DrainQueue(int max_keys) {
    int done = 0;
    std::string key;
    while (done < max_keys && queue_.Get(&key, 0)) {
      if (ReconcileKey(key))
        queue_.Forget(key);
      else
        queue_.AddRateLimited(key);
      queue_.Done(key);
      ++done;
    }
    if (queue_.TakeResyncNeeded()) resync_owed_ = true;
    return done;
  }

  // Event-driven sleep: the informer streams (already open — they live
  // through reconcile passes) and, when ``policy_stream``, a streaming
  // `?watch=1` on the policy CR are pumped for the whole interval (the
  // controller-runtime model — zero GET probes), with the status
  // listener served between waits and the bundle dir's LOCAL fingerprint
  // checked at the probe cadence. Operand drift is repaired IN PLACE —
  // informer events are classified into the workqueue and drained here,
  // O(1) requests per event, without ending the sleep or triggering a
  // full pass. Returns true when the sleep was fully handled (ran out,
  // or a policy/bundle change cut it short); false = the POLICY stream
  // could not be established or died — the caller falls back to
  // GET-probe polling for the remaining *left_ms.
  bool SleepOnWatches(int* left_ms, const std::string& bundle_fp,
                      bool policy_stream) {
    int secs = (*left_ms + 999) / 1000 + 1;
    std::string err;
    // Catch-up probe BEFORE opening the policy stream (so it lands
    // outside the event-driven window the tests pin to zero probes): a
    // policy edit that landed while the pass ran is honored now. Operand
    // drift needs no catch-up read at all — the informer streams stayed
    // open through the pass, so mid-pass events are already sitting in
    // the workqueue (or were re-queued by Done()).
    if (policy_stream && PolicyProbeSaysReconcile()) return true;
    kubeclient::WatchStream pws;
    if (policy_stream) {
      std::string path = PolicyPath() + "?watch=1&timeoutSeconds=" +
                         std::to_string(secs);
      if (!pws.Open(cfg_, path, secs + 30, &err)) {
        fprintf(stderr,
                "tpu-operator: watch unavailable (%s); falling back to "
                "generation polling\n", err.c_str());
        return false;
      }
    }
    // Wall-clock accounting for EVERY branch: a writer flapping the CR's
    // status at high rate streams kEvent results continuously, and a loop
    // that only deducts time in the idle branch would spin here past
    // the interval — for a leader, past the lease renewal deadline
    // (split-brain by starvation). left is recomputed from the clock.
    struct timespec sleep_start;
    clock_gettime(CLOCK_MONOTONIC, &sleep_start);
    const int budget_ms = *left_ms;
    auto recompute_left = [&]() {
      *left_ms = std::max(0, budget_ms - kubeclient::ElapsedMs(sleep_start));
    };
    int since_bundle_check = 0;
    // Consecutive-kEvent cap: a saturating stream (or a misbehaving proxy
    // echoing garbage lines) keeps Next(0) returning kEvent, so the loop
    // would never reach the idle branch where the status listener is
    // pumped — and the kubelet's /healthz probe (1 s timeout) would go
    // unanswered. Every kMaxEventDrain events the listener gets a
    // zero-length Pump before draining continues.
    constexpr int kMaxEventDrain = 64;
    int events_since_pump = 0;
    auto pump_guard = [&]() {
      if (++events_since_pump >= kMaxEventDrain) {
        events_since_pump = 0;
        Sleep(0);  // answer pending /healthz before draining more
      }
    };
    while (!g_stop) {
      recompute_left();
      if (*left_ms <= 0) break;
      bool idle = true;
      // Drain the watch streams WITHOUT blocking, then hand the actual
      // wait to Sleep() — the status listener is single-threaded and
      // only served inside its Pump; blocking in Next for the whole
      // interval would leave the kubelet's /healthz readiness probe
      // unanswered (default probe timeout: 1 s).
      if (policy_stream) {
        std::string line;
        kubeclient::WatchStream::Result r = pws.Next(0, &line);
        switch (r) {
          case kubeclient::WatchStream::kEvent: {
            idle = false;
            pump_guard();
            minijson::ValuePtr ev = minijson::Parse(line);
            if (!ev) break;
            std::string type =
                ev->Get("type") ? ev->Get("type")->as_string() : "";
            if (type == "ERROR") {
              // apiserver watch-level error (expired/internal): the stream
              // is useless but the CR state is UNKNOWN — fall back to the
              // probe loop rather than reconciling on it (a persistent
              // error would otherwise bypass --interval as a reconcile hot
              // loop, since each "successful" pass resets the backoff).
              fprintf(stderr, "tpu-operator: watch ERROR event; falling "
                      "back to generation polling\n");
              return false;
            }
            if (type == "DELETED") {
              if (!policy_missing_) {
                fprintf(stderr, "tpu-operator: policy %s deleted (watch); "
                        "reconciling now\n", opt_.policy.c_str());
                trace_.AddInstant("drift-event", "watch",
                                  {{"object", opt_.policy},
                                   {"via", "policy-watch"}});
                policy_dirty_ = true;
                return true;
              }
              break;
            }
            minijson::ValuePtr obj = ev->Get("object");
            if (!obj || !obj->Get("metadata")) {
              // Not a watch event at all: an apiserver error body (kind:
              // Status from a 403/410 response) streamed through the https
              // transport line-by-line. Reconciling on it would reset the
              // backoff each pass — a hot loop bypassing --interval for as
              // long as the error persists. The stream is junk; fall back
              // to generation polling for the remaining interval.
              fprintf(stderr, "tpu-operator: watch line without "
                      "object.metadata (apiserver error body?); falling "
                      "back to generation polling\n");
              return false;
            }
            double gen = ev->PathNumber("object.metadata.generation", 0);
            // Generation-filtered, like controller-runtime predicates: the
            // operator's own status PATCH echoes back as MODIFIED with an
            // unchanged generation and must not retrigger it.
            if (policy_missing_ || gen != policy_generation_) {
              fprintf(stderr,
                      "tpu-operator: policy %s changed (watch event, "
                      "generation %.0f -> %.0f); reconciling now\n",
                      opt_.policy.c_str(), policy_generation_, gen);
              kubeapi::TraceEmitter::Args dargs = {
                  {"object", opt_.policy}, {"via", "policy-watch"}};
              std::string tp = obj ? AnnotationTraceparent(*obj) : "";
              if (!tp.empty()) {
                dargs.push_back({"traceparent", tp});
                dargs.push_back(
                    {"trace_id", kubeapi::ParseTraceparent(tp).first});
              }
              trace_.AddInstant("drift-event", "watch", dargs);
              policy_dirty_ = true;
              return true;
            }
            break;
          }
          case kubeclient::WatchStream::kTimeout:
            break;  // nothing pending on the CR stream
          case kubeclient::WatchStream::kClosed:
          case kubeclient::WatchStream::kError:
            // server ended the stream early or transport broke: the
            // remaining sleep falls back to the probe loop
            recompute_left();
            return false;
        }
      }
      // Informer pump + queue drain: drift events are classified and
      // repaired right here — O(events) work inside the sleep, the sleep
      // itself keeps running (the interval pass stays a pure resync
      // backstop instead of the repair path).
      if (PumpInformers() > 0) idle = false;
      // NOT inside DrainQueue itself: the mid-pass drain (stage-gate
      // loop) runs while ReconcilePass iterates bundle_ by index, where
      // swapping the vector would invalidate the pass; here the pass is
      // over and the queue is the only consumer.
      if (queue_.depth() > 0) RefreshBundleForRepair();
      if (DrainQueue(16) > 0) idle = false;
      if (resync_owed_) {
        // the workqueue shed oldest keys under pressure: per-key repair
        // lost track of WHICH drifted, so owe one full resync round
        resync_owed_ = false;
        fprintf(stderr, "tpu-operator: workqueue shed oldest items under "
                "pressure; full resync now\n");
        return true;
      }
      if (!idle) continue;  // events flowed; wall clock rechecked on top
      // Nothing pending on any stream: serve status/healthz for a short
      // chunk (also the loop's sleep), and check the local inputs at the
      // probe cadence. left_ms itself is wall-clock-recomputed at the
      // loop top.
      int chunk = std::min(*left_ms, std::min(opt_.policy_poll_ms, 100));
      Sleep(chunk);
      since_bundle_check += chunk;
      if (since_bundle_check >= opt_.policy_poll_ms) {
        since_bundle_check = 0;
        std::string fp = BundleFingerprint();
        if (!fp.empty() && fp != bundle_fp) {
          fprintf(stderr,
                  "tpu-operator: bundle changed on disk; reconciling "
                  "now\n");
          return true;
        }
        // Without a policy stream (--no-policy-watch) the CR's
        // generation is still probed at the same cadence, so a day-2
        // toggle cuts an operand-watch sleep short exactly like it cuts
        // the plain probe loop short.
        if (!policy_stream && !opt_.policy.empty() &&
            PolicyProbeSaysReconcile())
          return true;
      }
    }
    return true;
  }

  // One generation probe of the policy CR; true = reconcile now (the CR
  // changed, or was deleted — fail-open must kick in). ONE copy shared by
  // the probe fallback loop and the operand-watch idle branch so the two
  // cadences can never diverge. Probe errors (non-404) keep sleeping: a
  // flapping apiserver must not cut every sleep short.
  bool PolicyProbeSaysReconcile() {
    kubeclient::Response get = kubeclient::Call(cfg_, "GET", PolicyPath());
    if (!get.ok()) {
      if (get.status == 404 && !policy_missing_) {  // CR deleted
        policy_dirty_ = true;
        return true;
      }
      return false;
    }
    minijson::ValuePtr cr = minijson::Parse(get.body);
    if (!cr) return false;
    double gen = cr->PathNumber("metadata.generation", 0);
    if (policy_missing_ || gen != policy_generation_) {
      fprintf(stderr,
              "tpu-operator: policy %s changed (generation %.0f -> %.0f); "
              "reconciling now\n",
              opt_.policy.c_str(), policy_generation_, gen);
      policy_dirty_ = true;
      return true;
    }
    return false;
  }

  // Sleep up to ms, reacting to input changes so a day-2 edit reconciles
  // within seconds instead of waiting out the interval (or a post-failure
  // backoff):
  //  - the TpuStackPolicy CR, via a streaming watch when available (the
  //    upstream operator is controller-runtime, i.e. watch-driven), with
  //    a metadata.generation GET probe every policy_poll_ms as fallback
  //    (errors fall back to the normal cadence — a flapping apiserver
  //    must not turn the watch into a retry storm),
  //  - the owned workload operands, via streaming collection watches, so
  //    external drift (kubectl delete/edit of a DaemonSet) is repaired on
  //    the event instead of the next interval pass,
  //  - the bundle dir's fingerprint (local stats; a re-rendered ConfigMap
  //    rolls out as soon as kubelet projects it).
  void SleepWatchingInputs(int ms) {
    if (opt_.policy_poll_ms <= 0) {
      Sleep(ms);
      return;
    }
    // Baseline = the fingerprint captured at PASS START (not now): a
    // re-render that landed mid-pass wasn't reconciled by the pass that
    // just finished and must cut this sleep short immediately.
    const std::string& bundle_fp = pass_bundle_fp_;
    int left = ms;
    // The watches are gated like the remote probe below: never during a
    // failure backoff (the apiserver is likely the thing that is down).
    bool policy_stream = opt_.policy_watch && !opt_.policy.empty() &&
                         healthy_;
    bool operand_stream = UseInformers() && healthy_ && !informers_.empty();
    if (policy_stream || operand_stream) {
      double ws_ts = trace_.NowUs();
      bool handled = SleepOnWatches(&left, bundle_fp, policy_stream);
      trace_.AddComplete("watch-sleep", "watch", ws_ts,
                         trace_.NowUs() - ws_ts,
                         {{"handled", handled ? "true" : "false"}});
      if (handled) return;
      if (left <= 0 || g_stop) return;
    }
    while (left > 0 && !g_stop) {
      int chunk = std::min(left, opt_.policy_poll_ms);
      Sleep(chunk);
      left -= chunk;
      if (left <= 0 || g_stop) break;
      std::string fp = BundleFingerprint();
      if (!fp.empty() && fp != bundle_fp) {
        fprintf(stderr,
                "tpu-operator: bundle changed on disk; reconciling now\n");
        break;
      }
      // The policy probe is a remote GET: skip it during a failure backoff
      // (the apiserver is likely the thing that's down — a fleet of
      // operators polling it at 2s would undo the backoff). The bundle
      // probe above is local stats and stays live regardless.
      if (opt_.policy.empty() || !healthy_) continue;
      if (PolicyProbeSaysReconcile()) break;
    }
  }

  std::string StatusJson() const {
    minijson::ValuePtr root = minijson::Value::MakeObject();
    root->Set("passes", std::make_shared<minijson::Value>(double(passes_)));
    root->Set("healthy", std::make_shared<minijson::Value>(healthy_));
    root->Set("consecutiveFailures", std::make_shared<minijson::Value>(
                                         double(consecutive_failures_)));
    if (!last_error_.empty())
      root->Set("lastError", std::make_shared<minijson::Value>(last_error_));
    auto arr = minijson::Value::MakeArray();
    for (const auto& bo : bundle_) {
      auto o = minijson::Value::MakeObject();
      o->Set("file", std::make_shared<minijson::Value>(bo.file));
      o->Set("stage", std::make_shared<minijson::Value>(bo.stage));
      o->Set("applied", std::make_shared<minijson::Value>(bo.applied));
      o->Set("ready", std::make_shared<minijson::Value>(bo.ready));
      if (bo.disabled)
        o->Set("disabled", std::make_shared<minijson::Value>(true));
      if (!bo.error.empty())
        o->Set("error", std::make_shared<minijson::Value>(bo.error));
      arr->Append(o);
    }
    root->Set("objects", arr);
    if (opt_.leader_elect) {
      root->Set("role", std::make_shared<minijson::Value>(
                            std::string(leader_ ? "leader" : "standby")));
      root->Set("identity", std::make_shared<minijson::Value>(identity_));
    }
    if (!opt_.policy.empty()) {
      auto p = minijson::Value::MakeObject();
      p->Set("name", std::make_shared<minijson::Value>(opt_.policy));
      p->Set("generation",
             std::make_shared<minijson::Value>(policy_generation_));
      p->Set("missing", std::make_shared<minijson::Value>(policy_missing_));
      root->Set("policy", p);
    }
    if (!informers_.empty()) {
      // per-collection informer state: synced flag, cached object count,
      // and how many (re)LISTs it has cost — the O(events) audit trail
      auto infs = minijson::Value::MakeObject();
      for (const auto& kv : informers_) {
        auto o = minijson::Value::MakeObject();
        o->Set("synced",
               std::make_shared<minijson::Value>(kv.second->synced()));
        o->Set("objects", std::make_shared<minijson::Value>(
                              double(kv.second->objects().size())));
        o->Set("relists", std::make_shared<minijson::Value>(
                              double(kv.second->relists())));
        infs->Set(kv.first, o);
      }
      root->Set("informers", infs);
    }
    return root->Dump() + "\n";
  }

  // Reconcile-duration histogram buckets (seconds), FIXED so two
  // operators' scrapes aggregate bucket-for-bucket. A pass spans apply +
  // readiness gates, so the tail reaches minutes; +Inf is implicit.
  static constexpr double kReconcileBucketsS[] = {
      0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60};
  static constexpr size_t kReconcileBuckets =
      sizeof(kReconcileBucketsS) / sizeof(kReconcileBucketsS[0]);

  void ObserveReconcileSeconds(double secs) {
    // shared bucket math (kubeapi::HistogramBucketIndex, selftest- and
    // parity-pinned): a value EXACTLY equal to a bound lands in that
    // bucket on both sides of the Python/C++ twin
    size_t idx = kubeapi::HistogramBucketIndex(secs, kReconcileBucketsS,
                                               kReconcileBuckets);
    ++reconcile_counts_[idx];
    reconcile_sum_s_ += secs;
    ++reconcile_count_;
  }

  std::string Metrics() const {
    int applied = 0, ready = 0, disabled = 0;
    for (const auto& bo : bundle_) {
      applied += bo.applied;
      ready += bo.ready;
      disabled += bo.disabled;
    }
    char buf[1024];
    snprintf(buf, sizeof(buf),
             "# TYPE tpu_operator_objects gauge\n"
             "tpu_operator_objects{state=\"desired\"} %zu\n"
             "tpu_operator_objects{state=\"applied\"} %d\n"
             "tpu_operator_objects{state=\"ready\"} %d\n"
             "tpu_operator_objects{state=\"disabled\"} %d\n"
             "# TYPE tpu_operator_passes_total counter\n"
             "tpu_operator_passes_total %d\n"
             "# TYPE tpu_operator_healthy gauge\n"
             "tpu_operator_healthy %d\n"
             "# TYPE tpu_operator_consecutive_failures gauge\n"
             "tpu_operator_consecutive_failures %d\n"
             "# TYPE tpu_operator_policy_generation gauge\n"
             "tpu_operator_policy_generation %.0f\n",
             bundle_.size(), applied, ready, disabled, passes_,
             healthy_ ? 1 : 0, consecutive_failures_, policy_generation_);
    std::string out = buf;
    // Telemetry families (ISSUE 6; names pinned via
    // kubeapi::OperatorMetricNames() — the telemetry.py twin table).
    // Histogram: Prometheus cumulative `le` encoding.
    out += "# TYPE tpu_operator_reconcile_duration_seconds histogram\n";
    long cum = 0;
    for (size_t i = 0; i < kReconcileBuckets; ++i) {
      cum += reconcile_counts_[i];
      snprintf(buf, sizeof(buf),
               "tpu_operator_reconcile_duration_seconds_bucket"
               "{le=\"%g\"} %ld\n",
               kReconcileBucketsS[i], cum);
      out += buf;
    }
    snprintf(buf, sizeof(buf),
             "tpu_operator_reconcile_duration_seconds_bucket"
             "{le=\"+Inf\"} %ld\n"
             "tpu_operator_reconcile_duration_seconds_sum %.6f\n"
             "tpu_operator_reconcile_duration_seconds_count %ld\n",
             reconcile_count_, reconcile_sum_s_, reconcile_count_);
    out += buf;
    // Watch-path churn + the informer-core gauges: queue depth is the
    // LIVE workqueue occupancy (keys awaiting Reconcile(key), delayed
    // retries excluded); sync lag is informer-cache STALENESS — seconds
    // since the most-stale collection was last proven fresh (completed
    // list, delivered event, or a clean watch-window expiry), bounded by
    // ~watch_window_s on a healthy stream and growing without bound when
    // the apiserver is gone. Request-driven modes (--once,
    // --no-operand-watch) keep the old meaning: seconds since the last
    // converged pass (from process start until the first one).
    double lag_s = 0;
    bool any_informer = false;
    for (const auto& kv : informers_) {
      if (!kv.second->synced()) continue;
      any_informer = true;
      lag_s = std::max(lag_s, kv.second->StalenessSeconds());
    }
    if (!any_informer) {
      // seconds computed directly from the timespec (NOT ElapsedMs, whose
      // int-milliseconds return overflows after ~24.8 days — exactly the
      // long-outage case this gauge exists to expose)
      struct timespec now;
      clock_gettime(CLOCK_MONOTONIC, &now);
      const struct timespec& sync_ref = synced_ ? last_sync_ : start_ts_;
      lag_s = static_cast<double>(now.tv_sec - sync_ref.tv_sec) +
              (now.tv_nsec - sync_ref.tv_nsec) / 1e9;
      if (lag_s < 0) lag_s = 0;
    }
    long reconnects = watch_reconnects_;
    for (const auto& kv : informers_) reconnects += kv.second->reconnects();
    snprintf(buf, sizeof(buf),
             "# TYPE tpu_operator_watch_reconnects_total counter\n"
             "tpu_operator_watch_reconnects_total %ld\n"
             "# TYPE tpu_operator_queue_depth gauge\n"
             "tpu_operator_queue_depth %zu\n"
             "# TYPE tpu_operator_sync_lag_seconds gauge\n"
             "tpu_operator_sync_lag_seconds %.3f\n",
             reconnects, queue_.depth(), lag_s);
    out += buf;
    // Workqueue families (twin-table pinned in kubeapi.cc/telemetry.py):
    // adds meters classification pressure, retries the backoff re-queues,
    // depth the live occupancy again under its workqueue-family name.
    snprintf(buf, sizeof(buf),
             "# TYPE tpu_operator_workqueue_adds_total counter\n"
             "tpu_operator_workqueue_adds_total %lld\n"
             "# TYPE tpu_operator_workqueue_retries_total counter\n"
             "tpu_operator_workqueue_retries_total %lld\n"
             "# TYPE tpu_operator_workqueue_depth gauge\n"
             "tpu_operator_workqueue_depth %zu\n",
             queue_.adds(), queue_.retries(), queue_.depth());
    out += buf;
    if (opt_.leader_elect)
      out += "# TYPE tpu_operator_leader gauge\n"
             "tpu_operator_leader " + std::to_string(leader_ ? 1 : 0) + "\n";
    return out;
  }

  bool healthy() const { return healthy_; }
  void set_healthy(bool h) { healthy_ = h; }

  // Atomically rewrite --trace-out from the bounded trace ring (tmp +
  // rename, the journal's torn-tail discipline): a SIGKILL at any
  // instant leaves the previous dump or the complete new one, never
  // torn JSON. Best-effort — an unwritable path must not fail a pass.
  void DumpTrace() {
    if (opt_.trace_out.empty()) return;
    // mkstemp, not a predictable ".tmp" sibling: a fixed scratch name
    // in a shared directory is symlink-plantable (CWE-377) — the same
    // discipline the Python twin's _atomic_write keeps
    std::string tmp = opt_.trace_out + ".XXXXXX";
    int fd = mkstemp(&tmp[0]);
    if (fd < 0) return;
    std::string doc = trace_.DumpChromeJson();
    size_t off = 0;
    bool ok = true;
    while (off < doc.size()) {
      ssize_t n = write(fd, doc.data() + off, doc.size() - off);
      if (n <= 0) {
        ok = false;
        break;
      }
      off += static_cast<size_t>(n);
    }
    fsync(fd);
    close(fd);
    if (ok)
      rename(tmp.c_str(), opt_.trace_out.c_str());
    else
      remove(tmp.c_str());
  }

 private:
  // The /healthz body: "ok" when converged; otherwise the degraded-state
  // detail — how many consecutive passes failed and the latest error — so
  // a flapping apiserver reads as "reconcile failing: 3 consecutive
  // failure(s); last: 20-plugin--daemonset.json: POST ... -> 503 ..."
  // instead of a bare 503 with the story buried in pod logs.
  std::string HealthBody() const {
    if (healthy_) return "ok\n";
    if (lease_error_)
      return "leader-election lease unverifiable "
             "(RBAC/namespace/transport)\n";
    if (consecutive_failures_ == 0) return "not yet converged\n";
    std::string out = "reconcile failing: " +
                      std::to_string(consecutive_failures_) +
                      " consecutive failure(s)";
    if (!last_error_.empty()) out += "; last: " + last_error_.substr(0, 400);
    out += "\n";
    return out;
  }

  void Sleep(int ms) {
    if (!status_.enabled()) {
      // no status listener: plain sleep, skip serializing state every poll
      for (int left = ms; left > 0 && !g_stop; left -= 50)
        usleep(std::min(left, 50) * 1000);
      return;
    }
    status_.Pump(ms, StatusJson(), Metrics(), healthy_, HealthBody());
  }

  // --- TpuStackPolicy (ClusterPolicy analog) ---------------------------

  std::string PolicyPath() const { return kPolicyPathPrefix + opt_.policy; }

  // Whether this pass needs to GET the CR at all. With the informer core
  // and the policy watch both running, the CR is only re-fetched when
  // something marked it dirty (a watch event, a probe hit, a failed
  // fetch) — an idle interval costs zero policy reads too. Request-driven
  // modes (--once, --no-operand-watch, --no-policy-watch) keep the
  // fetch-every-pass behavior.
  bool ShouldFetchPolicy() const {
    if (opt_.policy.empty()) return false;
    if (!UseInformers() || !opt_.policy_watch) return true;
    return policy_dirty_ || !policy_seen_ || policy_missing_;
  }

  // Poll the CR once per pass. Fail-open semantics: a missing CR enables
  // everything (deleting the CR must not tear the stack down), and a
  // transport error keeps the last known policy (a flapping apiserver must
  // not flap operands in and out of the cluster).
  void FetchPolicy() {
    if (opt_.policy.empty()) return;
    kubeclient::Response get = kubeclient::Call(cfg_, "GET", PolicyPath());
    if (get.ok()) {
      minijson::ValuePtr cr = minijson::Parse(get.body);
      if (!cr || !cr->is_object()) {
        fprintf(stderr, "tpu-operator: policy %s: unparseable body; "
                "keeping last known policy\n", opt_.policy.c_str());
        return;
      }
      std::map<std::string, bool> enabled;
      minijson::ValuePtr spec = cr->Get("spec");
      minijson::ValuePtr ops = spec ? spec->Get("operands") : nullptr;
      if (ops && ops->is_object()) {
        for (const auto& kv : ops->items()) {
          minijson::ValuePtr e = kv.second ? kv.second->Get("enabled")
                                           : nullptr;
          // absent `enabled` means enabled — a partial CR only turns
          // operands OFF explicitly
          enabled[kv.first] = e && e->is_bool() ? e->as_bool() : true;
        }
      }
      if (policy_missing_)
        fprintf(stderr, "tpu-operator: policy %s found; gating resumed\n",
                opt_.policy.c_str());
      policy_enabled_ = std::move(enabled);
      policy_generation_ = cr->PathNumber("metadata.generation", 0);
      policy_seen_ = true;
      policy_missing_ = false;
      policy_dirty_ = false;
    } else if (get.status == 404) {
      if (!policy_missing_)
        fprintf(stderr, "tpu-operator: policy %s not found; all operands "
                "enabled (fail-open)\n", opt_.policy.c_str());
      policy_missing_ = true;
      policy_enabled_.clear();
    } else {
      fprintf(stderr, "tpu-operator: policy fetch -> %d %s; keeping last "
              "known policy\n", get.status,
              get.status ? get.body.substr(0, 160).c_str()
                         : get.error.c_str());
      policy_dirty_ = true;  // stale: retry next pass even when gated
    }
  }

  // Gating: the live policy wins; without one (CR deleted, no --policy,
  // or an operand key the CR doesn't mention) the object's install-time
  // default applies — fail-open reverts to the installed state and never
  // deploys a spec-disabled operand.
  bool OperandEnabled(const std::string& operand,
                      bool default_enabled) const {
    if (operand.empty()) return true;  // un-gated (the namespace itself)
    auto it = policy_enabled_.find(operand);
    return it == policy_enabled_.end() ? default_enabled : it->second;
  }

  // Remove a policy-disabled operand object from the cluster. Idempotent:
  // already-absent is success. Probes with a GET first so the steady state
  // (object long gone) costs a read, not a DELETE landing in the audit log
  // every pass; only an actual removal is logged.
  bool DeleteDisabled(BundleObject* bo) {
    bo->disabled = true;
    std::string err;
    std::string obj_path = kubeapi::ObjectPath(*bo->obj, &err);
    if (obj_path.empty()) {
      bo->error = err;
      return false;
    }
    kubeclient::Response get = kubeclient::Call(cfg_, "GET", obj_path);
    if (get.status == 404) return true;
    if (!get.ok()) {
      bo->error = "GET " + obj_path + " -> " + std::to_string(get.status) +
                  " " + (get.status ? get.body.substr(0, 160) : get.error);
      return false;
    }
    kubeclient::Response del = kubeclient::Call(cfg_, "DELETE", obj_path);
    if (del.ok() || del.status == 404) {
      fprintf(stderr, "tpu-operator: operand %s disabled by policy: "
              "deleted %s\n", bo->operand.c_str(), bo->file.c_str());
      return true;
    }
    bo->error = "DELETE " + obj_path + " -> " + std::to_string(del.status) +
                " " + (del.status ? del.body.substr(0, 160) : del.error);
    return false;
  }

  // Report observed state through the CR's status subresource — what
  // `kubectl get tsp` renders (observedGeneration gates staleness the same
  // way the workload readiness checks do).
  void WritePolicyStatus(bool pass_ok) {
    if (opt_.policy.empty() || !policy_seen_ || policy_missing_) return;
    using minijson::Value;
    struct Agg { int total = 0, applied = 0, ready = 0;
                 bool default_enabled = true; };
    std::map<std::string, Agg> per;
    int want = 0, have = 0;
    for (const auto& bo : bundle_) {
      if (bo.operand.empty()) continue;
      Agg& a = per[bo.operand];
      ++a.total;
      a.applied += bo.applied;
      a.ready += bo.ready;
      a.default_enabled = bo.default_enabled;
      // "enabled" reports the FETCHED policy, not this pass's deletion
      // progress — a pass that fails before reaching a disabled operand's
      // stage must not report the toggle as un-honored
      if (OperandEnabled(bo.operand, bo.default_enabled)) {
        ++want;
        have += bo.ready;
      }
    }
    auto ops = Value::MakeObject();
    for (const auto& kv : per) {
      const Agg& a = kv.second;
      bool enabled = OperandEnabled(kv.first, kv.second.default_enabled);
      auto o = Value::MakeObject();
      o->Set("enabled", std::make_shared<Value>(enabled));
      o->Set("applied", std::make_shared<Value>(a.applied == a.total));
      o->Set("ready", std::make_shared<Value>(
          enabled && a.ready == a.total));
      ops->Set(kv.first, o);
    }
    auto st = Value::MakeObject();
    st->Set("observedGeneration",
            std::make_shared<Value>(policy_generation_));
    st->Set("phase", std::make_shared<Value>(
        std::string(pass_ok ? "Ready" : "Progressing")));
    st->Set("readySummary", std::make_shared<Value>(
        std::to_string(have) + "/" + std::to_string(want) + " ready"));
    st->Set("operands", ops);
    // Dedup on the timestamp-free content: an idle resync that computed
    // the same status skips the PATCH entirely (lastReconcileTime alone
    // would otherwise make every pass a write — churning the CR's
    // resourceVersion and waking every policy watcher in the fleet).
    std::string fp = st->Dump();
    if (fp == last_status_written_) return;
    st->Set("lastReconcileTime", std::make_shared<Value>(NowRfc3339()));
    auto root = Value::MakeObject();
    root->Set("status", st);
    // best-effort, like Events: status delivery must never fail the pass
    kubeclient::Response r =
        kubeclient::Call(cfg_, "PATCH", PolicyPath() + "/status",
                         root->Dump(), "application/merge-patch+json");
    if (r.ok()) last_status_written_ = fp;
  }

  // The namespace reconcile failures are reported into. Cluster-scoped
  // bundle objects (the stage-00 Namespace/ClusterRole themselves) have no
  // namespace of their own, and apiserver core/v1 Event validation requires
  // the Event's namespace to be 'default' when involvedObject.namespace is
  // empty — posting such events into the operand namespace gets them
  // 422-rejected and silently dropped (the POST is best-effort).
  std::string EventNamespace(const minijson::Value& involved) const {
    std::string ns = involved.PathString("metadata.namespace");
    return ns.empty() ? "default" : ns;
  }

  // Surface a reconcile problem as a Kubernetes Event on the operand
  // object (`kubectl describe ds ...` / `kubectl get events` visibility,
  // like the reference's gpu-operator). Best-effort: event delivery must
  // never change reconcile behavior, and an unreachable apiserver would
  // fail the POST exactly when the pass already failed.
  void EmitEvent(const std::string& reason, const std::string& message,
                 const BundleObject& bo) {
    using minijson::Value;
    const minijson::Value& involved = *bo.obj;
    std::string ns = EventNamespace(involved);
    auto ev = Value::MakeObject();
    ev->Set("apiVersion", std::make_shared<Value>(std::string("v1")));
    ev->Set("kind", std::make_shared<Value>(std::string("Event")));
    auto meta = Value::MakeObject();
    meta->Set("name", std::make_shared<Value>(
        "tpu-operator." + std::to_string(time(nullptr)) + "." +
        std::to_string(++event_seq_)));
    meta->Set("namespace", std::make_shared<Value>(ns));
    ev->Set("metadata", meta);
    auto obj = Value::MakeObject();
    obj->Set("apiVersion", std::make_shared<Value>(
        involved.PathString("apiVersion")));
    obj->Set("kind", std::make_shared<Value>(involved.PathString("kind")));
    obj->Set("name", std::make_shared<Value>(
        involved.PathString("metadata.name")));
    obj->Set("namespace", std::make_shared<Value>(
        involved.PathString("metadata.namespace")));
    // kubectl describe filters events on involvedObject.uid — without the
    // live object's uid the Event only shows in `kubectl get events`
    if (!bo.uid.empty())
      obj->Set("uid", std::make_shared<Value>(bo.uid));
    ev->Set("involvedObject", obj);
    ev->Set("reason", std::make_shared<Value>(reason));
    ev->Set("message", std::make_shared<Value>(message.substr(0, 1024)));
    ev->Set("type", std::make_shared<Value>(std::string("Warning")));
    auto src = Value::MakeObject();
    src->Set("component", std::make_shared<Value>(
        std::string("tpu-operator")));
    ev->Set("source", src);
    std::string now = NowRfc3339();
    ev->Set("firstTimestamp", std::make_shared<Value>(now));
    ev->Set("lastTimestamp", std::make_shared<Value>(now));
    ev->Set("count", std::make_shared<Value>(1.0));
    std::string err;
    std::string coll = kubeapi::CollectionPath(*ev, &err);
    if (!coll.empty()) kubeclient::Call(cfg_, "POST", coll, ev->Dump());
  }

  // Remember the live object's metadata.uid (event correlation — kubectl
  // describe matches on it) and metadata.generation (the drift watch's
  // change filter) from an API response body.
  void RememberUid(BundleObject* bo, const std::string& body) {
    minijson::ValuePtr live = minijson::Parse(body);
    if (live) {
      std::string uid = live->PathString("metadata.uid");
      if (!uid.empty()) bo->uid = uid;
      double gen = live->PathNumber("metadata.generation", 0);
      if (gen > 0) bo->generation = gen;
      // the tpuctl-stamped trace context, if the live object carries
      // one — this pass's apply-object slice names it
      std::string tp = AnnotationTraceparent(*live);
      if (!tp.empty()) bo->traceparent = tp;
    }
  }

  bool ApplyObject(BundleObject* bo) {
    std::string err;
    std::string obj_path = kubeapi::ObjectPath(*bo->obj, &err);
    if (obj_path.empty()) {
      bo->error = err;
      return false;
    }
    // Primary path: server-side apply — ONE apply PATCH under this
    // operator's field manager, no prior GET. force=true is deliberate:
    // reverting drift in our own operands is the reconcile contract, and
    // with per-field ownership the force only claims fields the bundle
    // actually specifies (tpuctl's co-applied fields carry equal values,
    // so the two managers co-own instead of fighting). 415/400 = the
    // apiserver predates SSA: flip the sticky fallback and use the
    // GET+merge-PATCH path below for the rest of this process's life.
    if (!ssa_unsupported_) {
      std::string apply_path = obj_path + "?fieldManager=" +
                               kubeapi::FieldManager() + "&force=true";
      kubeclient::Response applied =
          kubeclient::Call(cfg_, "PATCH", apply_path, bo->obj->Dump(),
                           "application/apply-patch+yaml");
      if (applied.ok()) {
        RememberUid(bo, applied.body);
        bo->applied = true;
        return true;
      }
      if (applied.status == 415 || applied.status == 400) {
        ssa_unsupported_ = true;
        fprintf(stderr,
                "tpu-operator: server-side apply unsupported (HTTP %d); "
                "falling back to GET+merge-PATCH for this process\n",
                applied.status);
        // fall through to the merge path (which also surfaces a genuine
        // 400 — a rejected manifest fails the POST/PATCH there too)
      } else {
        bo->error = "SSA PATCH " + obj_path + " -> " +
                    std::to_string(applied.status) + " " +
                    (applied.status ? applied.body.substr(0, 160)
                                    : applied.error);
        return false;
      }
    }
    kubeclient::Response get = kubeclient::Call(cfg_, "GET", obj_path);
    if (get.ok()) RememberUid(bo, get.body);
    if (get.status == 404) {
      std::string coll = kubeapi::CollectionPath(*bo->obj, &err);
      kubeclient::Response post =
          kubeclient::Call(cfg_, "POST", coll, bo->obj->Dump());
      if (post.status == 409) {
        // AlreadyExists despite our 404 read: stale-read window after an
        // apiserver bounce/HA failover (or a concurrent creator). The
        // object is there — patch it, don't fail the pass.
        kubeclient::Response patch =
            kubeclient::Call(cfg_, "PATCH", obj_path, bo->obj->Dump(),
                             "application/merge-patch+json");
        if (!patch.ok()) {
          bo->error = "PATCH after 409 " + obj_path + " -> " +
                      std::to_string(patch.status) + " " +
                      (patch.status ? patch.body.substr(0, 160)
                                    : patch.error);
          return false;
        }
        RememberUid(bo, patch.body);
      } else if (!post.ok()) {
        bo->error = "POST " + coll + " -> " + std::to_string(post.status) +
                    " " + (post.status ? post.body.substr(0, 160) : post.error);
        return false;
      } else {
        RememberUid(bo, post.body);
      }
    } else if (get.ok()) {
      // merge-patch the desired state over whatever is there — reverts
      // manual drift in our operands without clobbering server-set fields
      kubeclient::Response patch =
          kubeclient::Call(cfg_, "PATCH", obj_path, bo->obj->Dump(),
                           "application/merge-patch+json");
      if (!patch.ok()) {
        bo->error = "PATCH " + obj_path + " -> " +
                    std::to_string(patch.status) + " " +
                    (patch.status ? patch.body.substr(0, 160) : patch.error);
        return false;
      }
      RememberUid(bo, patch.body);  // the PATCH may have bumped generation
    } else {
      bo->error = "GET " + obj_path + " -> " + std::to_string(get.status) +
                  " " + (get.status ? get.body.substr(0, 160) : get.error);
      return false;
    }
    bo->applied = true;
    return true;
  }

  bool CheckReady(BundleObject* bo) {
    std::string kind = bo->obj->PathString("kind");
    if (kind != "DaemonSet" && kind != "Deployment" && kind != "Job") {
      bo->ready = true;
      return true;
    }
    std::string err;
    std::string obj_path = kubeapi::ObjectPath(*bo->obj, &err);
    kubeclient::Response get = kubeclient::Call(cfg_, "GET", obj_path);
    if (!get.ok()) return false;
    minijson::ValuePtr live = minijson::Parse(get.body);
    if (!live) return false;
    double gen = live->PathNumber("metadata.generation", 0);
    if (gen > 0) bo->generation = gen;
    bool ready = kubeapi::IsReady(*live);
    if (!ready && opt_.allow_empty_daemonsets && kind == "DaemonSet" &&
        live->PathNumber("status.desiredNumberScheduled", -1) == 0)
      ready = true;  // cluster has no matching nodes yet; don't wedge
    bo->ready = ready;
    return ready;
  }

  Options opt_;
  kubeclient::Config cfg_;
  std::vector<BundleObject> bundle_;
  StatusServer status_;
  // trace emitter (ISSUE 8): reconcile/apply/gate/watch slices, bounded
  // ring, dumped to --trace-out after each pass (see DumpTrace)
  kubeapi::TraceEmitter trace_;
  // Sticky server-side-apply capability (probed by the first apply of
  // the process): once an apply PATCH answers 415/400, every later
  // ApplyObject uses the GET+merge-PATCH path without re-probing.
  bool ssa_unsupported_ = false;
  int passes_ = 0;
  int event_seq_ = 0;
  bool healthy_ = false;
  // telemetry (ISSUE 6): reconcile-duration histogram (fixed buckets +
  // the +Inf overflow slot), watch reconnect counter (operand/policy
  // streams re-opened after an abnormal close), and the sync-lag clock
  // (last converged pass; process start until the first one)
  long reconcile_counts_[kReconcileBuckets + 1] = {0};
  double reconcile_sum_s_ = 0;
  long reconcile_count_ = 0;
  long watch_reconnects_ = 0;
  struct timespec start_ts_ = {0, 0};
  struct timespec last_sync_ = {0, 0};
  bool synced_ = false;
  // degraded-state surface (/healthz, /status, /metrics): consecutive
  // failed passes and the first error of the latest failed one
  int consecutive_failures_ = 0;
  std::string last_error_;
  // bundle-change tracking (input probe + prune gating)
  std::string pass_bundle_fp_;   // fingerprint at the current pass's start
  std::string repair_bundle_fp_; // last render the repair path re-read
  std::string last_pruned_fp_;   // fingerprint the last prune sweep covered
  // informer/workqueue core: one LIST+watch cache per owned collection,
  // the rate-limited dedup queue of drifted keys, and the desired-state
  // index (coll/name -> bundle_ slot) events are classified against.
  // Depth bound 4096 ≈ 2x the largest supported fleet bundle; shedding
  // flags resync_owed_ (repair-by-full-round instead of unbounded growth)
  std::map<std::string, std::unique_ptr<informer::Informer>> informers_;
  workqueue::RateLimitedQueue queue_{4096, 200, 30000};
  std::map<std::string, size_t> key_index_;
  std::map<std::string, time_t> ready_deadline_;  // event-repair gates
  bool resync_owed_ = false;
  bool policy_dirty_ = true;     // CR must be re-fetched next pass
  std::string last_status_written_;  // WritePolicyStatus dedup fingerprint
  // policy state (see FetchPolicy for the fail-open/stale semantics)
  std::map<std::string, bool> policy_enabled_;
  double policy_generation_ = 0;
  bool policy_seen_ = false;
  bool policy_missing_ = false;
  // leader election
  std::string identity_;
  bool leader_ = false;
  bool lease_error_ = false;
  time_t last_renew_ = 0;
  std::string observed_lease_;   // holder|renewTime last seen on a
  time_t observed_at_ = 0;       // foreign lease, and when WE saw it
};

bool FlagVal(const char* arg, const char* name, std::string* out) {
  size_t n = strlen(name);
  if (strncmp(arg, name, n) == 0 && arg[n] == '=') {
    *out = arg + n + 1;
    return true;
  }
  return false;
}

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  std::string sval;
  for (int i = 1; i < argc; ++i) {
    const char* a = argv[i];
    if (FlagVal(a, "--apiserver", &opt.apiserver)) continue;
    if (FlagVal(a, "--token-file", &opt.token_file)) continue;
    if (FlagVal(a, "--ca-file", &opt.ca_file)) continue;
    if (FlagVal(a, "--bundle-dir", &opt.bundle_dir)) continue;
    if (FlagVal(a, "--trace-out", &opt.trace_out)) continue;
    if (FlagVal(a, "--policy", &opt.policy)) continue;
    if (FlagVal(a, "--policy-poll-ms", &sval)) {
      opt.policy_poll_ms = atoi(sval.c_str());
      continue;
    }
    if (FlagVal(a, "--interval", &sval)) { opt.interval_s = atoi(sval.c_str()); continue; }
    if (FlagVal(a, "--stage-timeout", &sval)) { opt.stage_timeout_s = atoi(sval.c_str()); continue; }
    if (FlagVal(a, "--poll-ms", &sval)) { opt.poll_ms = atoi(sval.c_str()); continue; }
    if (FlagVal(a, "--status-port", &sval)) { opt.status_port = atoi(sval.c_str()); continue; }
    if (strcmp(a, "--once") == 0) { opt.once = true; continue; }
    if (strcmp(a, "--leader-elect") == 0) { opt.leader_elect = true; continue; }
    if (FlagVal(a, "--lease-duration", &sval)) {
      opt.lease_duration_s = atoi(sval.c_str());
      continue;
    }
    if (FlagVal(a, "--lease-name", &sval)) { opt.lease_name = sval; continue; }
    if (strcmp(a, "--allow-empty-daemonsets") == 0) {
      opt.allow_empty_daemonsets = true;
      continue;
    }
    if (strcmp(a, "--insecure-skip-tls-verify") == 0) {
      opt.insecure_skip_tls_verify = true;
      continue;
    }
    if (strcmp(a, "--no-policy-watch") == 0) {
      opt.policy_watch = false;  // GET-probe polling only (debug escape
                                 // hatch; the watch self-falls-back anyway)
      continue;
    }
    if (strcmp(a, "--no-operand-watch") == 0) {
      opt.operand_watch = false;  // interval-pass drift repair only (the
                                  // bench's poll arm; debug escape hatch)
      continue;
    }
    if (FlagVal(a, "--page-limit", &sval)) {
      opt.page_limit = atoi(sval.c_str());  // informer LIST page size
      continue;
    }
    if (FlagVal(a, "--watch-window", &sval)) {
      opt.watch_window_s = atoi(sval.c_str());  // watch timeoutSeconds
      continue;
    }
    fprintf(stderr,
            "tpu-operator: unknown flag %s\n"
            "usage: tpu-operator [--apiserver=URL] [--token-file=F] "
            "[--ca-file=F]\n"
            "  [--bundle-dir=DIR] [--trace-out=PATH] [--policy=NAME]\n"
            "  [--policy-poll-ms=MS]\n"
            "  [--no-policy-watch] [--no-operand-watch]\n"
            "  [--page-limit=N] [--watch-window=SECS]\n"
            "  [--interval=SECS] [--stage-timeout=SECS]\n"
            "  [--poll-ms=MS] [--status-port=PORT] [--once]\n"
            "  [--leader-elect] [--lease-duration=SECS] [--lease-name=N]\n"
            "  [--allow-empty-daemonsets] [--insecure-skip-tls-verify]\n",
            a);
    return 2;
  }

  kubeclient::Config cfg;
  if (!opt.apiserver.empty()) {
    cfg.base_url = opt.apiserver;
    if (!opt.token_file.empty())
      kubeclient::ReadFileTrim(opt.token_file, &cfg.token);
    cfg.ca_file = opt.ca_file;
  } else if (!kubeclient::Config::InCluster(&cfg)) {
    fprintf(stderr,
            "tpu-operator: not in-cluster and no --apiserver given\n");
    return 2;
  }
  // The explicit flag is the ONLY opt-in to unverified TLS — in-cluster too
  // (a broken CA projection must fail requests, not silently downgrade the
  // transport carrying the ServiceAccount token).
  cfg.insecure_skip_tls_verify = opt.insecure_skip_tls_verify;

  srand(static_cast<unsigned>(getpid() ^ time(nullptr)));
  signal(SIGINT, HandleSignal);
  signal(SIGTERM, HandleSignal);
  signal(SIGPIPE, SIG_IGN);

  Operator op(opt, cfg);
  if (!op.LoadOrReloadBundle()) return 1;
  if (!op.Listen()) {
    fprintf(stderr, "tpu-operator: cannot listen on status port %d\n",
            opt.status_port);
    return 1;
  }
  fprintf(stderr,
          "tpu-operator: %s, bundle=%s, status port %d\n",
          opt.once ? "single pass" : "reconciling",
          opt.bundle_dir.c_str(), opt.status_port);

  if (opt.once) {
    if (opt.leader_elect && !op.TryAcquireLease()) {
      if (op.lease_error()) return 1;  // config error, already logged
      // Inert standby: distinct exit code so scripts can tell "another
      // instance holds the lease" from a failed reconcile.
      fprintf(stderr, "tpu-operator: standby (lease held elsewhere); "
              "--once exits without reconciling\n");
      printf("%s", op.StatusJson().c_str());
      return 3;
    }
    bool ok = op.ReconcilePass();
    op.set_healthy(ok);
    printf("%s", op.StatusJson().c_str());
    op.ReleaseLease();
    op.DumpTrace();
    return ok ? 0 : 1;
  }
  op.RunForever();
  op.ReleaseLease();
  // SIGTERM lands here (g_stop): the final dump carries the last
  // watch-sleep/drift slices that no pass followed
  op.DumpTrace();
  return 0;
}
