// kubeclient — minimal kube-apiserver REST client for the tpu-operator.
//
// Two transports behind one interface:
//  - plain HTTP/1.1 over TCP for http:// base URLs (the in-process fake
//    apiserver in tests, or a `kubectl proxy` endpoint), implemented with
//    raw sockets — no third-party HTTP library in the image;
//  - HTTPS via exec of the system `curl` binary for in-cluster https://
//    apiserver access with the ServiceAccount token + cluster CA (the image
//    ships no TLS headers, and shipping our own TLS would be malpractice —
//    curl is present in every node image this stack targets).

#ifndef TPU_NATIVE_OPERATOR_KUBECLIENT_H_
#define TPU_NATIVE_OPERATOR_KUBECLIENT_H_

#include <time.h>

#include <string>

namespace kubeclient {

// Milliseconds since t0 (CLOCK_MONOTONIC). One shared copy of the
// timespec arithmetic — WatchStream, the operator's sleep accounting and
// its status pump all budget waits with it.
int ElapsedMs(const struct timespec& t0);

// Capped exponential backoff for watch reconnects: base_ms doubling per
// consecutive failure (attempt 1 = base_ms, attempt 2 = 2*base_ms, ...),
// clamped to cap_ms. A persistently kClosed/kError stream — an apiserver
// rejecting the watch verb, a proxy resetting long-lived GETs — must not
// tight-loop stream opens (on the https transport each open is a curl
// spawn). Overflow-safe for any attempt count; attempt < 1 is treated
// as 1, and degenerate base/cap inputs clamp instead of misbehaving.
int WatchBackoffMs(int attempt, int base_ms, int cap_ms);

// Shared failure taxonomy (the C++ twin of tpu_cluster.kubeapply's
// RetryPolicy, pinned by operator_selftest): transport status 0 and the
// throttling/gateway statuses 429/500/502/503/504 are worth retrying;
// every other status is either success or terminal (409 Conflict is
// resolved semantically by the apply path — re-GET then re-PATCH — never
// blindly retried).
bool RetryableStatus(int status);

// Retry-After from a LOWERCASED header block -> milliseconds (0 = absent
// or the http-date form, which callers treat as "use computed backoff").
// Fractional seconds are accepted (test servers use them); clamped to 1h.
int ParseRetryAfterMs(const std::string& lowered_headers);

// Decode one COMPLETE chunked transfer-encoded payload into *decoded.
// Returns true only when the stream TERMINATED (the 0-length final chunk
// was present); false = truncated or garbage — an unparseable size line,
// a negative size, chunk data cut off mid-stream, or EOF before the
// terminator. A false return means the caller must classify the reply as
// transport status 0 ("truncated chunked HTTP body"), never hand the
// decoded prefix to a JSON parser as a silently-short 200 — the TRUNCATE
// fault class a slow/dying apiserver produces. The hostile byte-vector
// table in operator_selftest (kHostileChunkVectors) is the shared
// Python<->C++ pin: tests/test_slowpath.py greps it and drives the same
// vectors through the Python client's transport (RetryableStatus
// pattern).
bool DecodeChunkedBody(const std::string& body, std::string* decoded);

struct Response {
  int status = 0;          // HTTP status; 0 = transport failure
  std::string body;
  std::string error;       // transport-level error when status == 0
  int retry_after_ms = 0;  // server-sent Retry-After (plain-http transport
                           // only; the curl path reports 0)
  bool ok() const { return status >= 200 && status < 300; }
};

struct Config {
  std::string base_url;     // e.g. https://10.96.0.1:443 or http://127.0.0.1:8001
  std::string token;        // bearer token ("" = none)
  std::string ca_file;      // CA bundle for https
  // Sent as User-Agent on every request. Doubles as the field-manager
  // name real apiservers record for NON-apply writes (the GET+merge-
  // PATCH fallback path): without it those fields would land in
  // managedFields under "curl/x.y", which `tpuctl verify`'s ownership
  // check would flag as foreign drift. Same parity fix as the Python
  // client's "User-Agent: tpuctl"; defaults to the operator's manager.
  std::string user_agent = "tpu-operator";
  // Without a ca_file, https requests FAIL unless this is set (sending a
  // ServiceAccount token over unverified TLS would hand cluster-admin-ish
  // credentials to any MITM). InCluster() sets it, loudly, when the
  // projected CA is unreadable; the CLI path requires the explicit flag.
  bool insecure_skip_tls_verify = false;
  int timeout_ms = 10000;
  // Capped request retries under RetryableStatus: total tries per Call
  // (1 = no retries), backed off via WatchBackoffMs(attempt, base, cap) —
  // the same machinery pacing watch reconnects — unless the server sent
  // Retry-After. Kept small by design: the operator is single-threaded
  // and its /healthz is not pumped while a Call sleeps, so the worst-case
  // added stall is base+2*base (~600 ms at the defaults).
  int max_attempts = 3;
  int retry_base_ms = 200;
  int retry_cap_ms = 2000;

  // In-cluster defaults: KUBERNETES_SERVICE_HOST/PORT env + the mounted
  // ServiceAccount token/CA. Returns false when not running in a cluster.
  static bool InCluster(Config* out);
};

// method: GET | POST | PUT | PATCH | DELETE. content_type applies when body
// is non-empty (Kubernetes needs application/merge-patch+json for PATCH).
// Retries RetryableStatus answers up to cfg.max_attempts (429/5xx blips and
// transport failures absorb here instead of failing the reconcile pass);
// the returned Response is the final attempt's.
Response Call(const Config& cfg, const std::string& method,
              const std::string& path, const std::string& body = "",
              const std::string& content_type = "application/json");

// Streaming watch (`?watch=1`): ONE long-lived GET whose response body is a
// newline-delimited stream of watch-event JSON objects — the
// controller-runtime model, replacing per-interval GET probes. Same two
// transports as Call: plain socket for http:// (decodes chunked transfer
// itself), `curl -sS -N` child for https:// (curl dechunks). Single
// threaded by design: the caller pumps Next() and owns the cadence.
class WatchStream {
 public:
  enum Result {
    kEvent,    // *line holds one complete event JSON line
    kTimeout,  // nothing arrived within wait_ms; stream still open
    kClosed,   // server ended the stream cleanly (watch timeoutSeconds)
    kError,    // transport/protocol failure; caller should fall back
  };
  WatchStream() = default;
  ~WatchStream();
  WatchStream(const WatchStream&) = delete;
  WatchStream& operator=(const WatchStream&) = delete;

  // path_and_query must already carry `?watch=1&timeoutSeconds=…`;
  // max_seconds bounds the whole stream (curl --max-time on the https
  // path). False + *err when the stream cannot be established.
  bool Open(const Config& cfg, const std::string& path_and_query,
            int max_seconds, std::string* err);
  Result Next(int wait_ms, std::string* line);
  void Close();
  bool is_open() const { return fd_ >= 0; }

 private:
  bool Decode();  // raw_ -> body_ (chunked-aware); false on parse error

  int fd_ = -1;
  pid_t pid_ = -1;          // curl child (https path); -1 = plain socket
  std::string hdr_file_;    // 0600 auth-header temp file (https path)
  bool headers_done_ = false;
  bool chunked_ = false;
  bool saw_final_chunk_ = false;
  long chunk_left_ = -1;    // -1 = expecting a chunk-size line
  std::string raw_;         // undecoded transport bytes
  std::string body_;        // decoded body not yet split into lines
};

// Read a whole file, stripping trailing newlines (token files etc.).
bool ReadFileTrim(const std::string& path, std::string* out);

}  // namespace kubeclient

#endif  // TPU_NATIVE_OPERATOR_KUBECLIENT_H_
