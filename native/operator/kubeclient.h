// kubeclient — minimal kube-apiserver REST client for the tpu-operator.
//
// Two transports behind one interface:
//  - plain HTTP/1.1 over TCP for http:// base URLs (the in-process fake
//    apiserver in tests, or a `kubectl proxy` endpoint), implemented with
//    raw sockets — no third-party HTTP library in the image;
//  - HTTPS via exec of the system `curl` binary for in-cluster https://
//    apiserver access with the ServiceAccount token + cluster CA (the image
//    ships no TLS headers, and shipping our own TLS would be malpractice —
//    curl is present in every node image this stack targets).

#ifndef TPU_NATIVE_OPERATOR_KUBECLIENT_H_
#define TPU_NATIVE_OPERATOR_KUBECLIENT_H_

#include <string>

namespace kubeclient {

struct Response {
  int status = 0;          // HTTP status; 0 = transport failure
  std::string body;
  std::string error;       // transport-level error when status == 0
  bool ok() const { return status >= 200 && status < 300; }
};

struct Config {
  std::string base_url;     // e.g. https://10.96.0.1:443 or http://127.0.0.1:8001
  std::string token;        // bearer token ("" = none)
  std::string ca_file;      // CA bundle for https
  // Without a ca_file, https requests FAIL unless this is set (sending a
  // ServiceAccount token over unverified TLS would hand cluster-admin-ish
  // credentials to any MITM). InCluster() sets it, loudly, when the
  // projected CA is unreadable; the CLI path requires the explicit flag.
  bool insecure_skip_tls_verify = false;
  int timeout_ms = 10000;

  // In-cluster defaults: KUBERNETES_SERVICE_HOST/PORT env + the mounted
  // ServiceAccount token/CA. Returns false when not running in a cluster.
  static bool InCluster(Config* out);
};

// method: GET | POST | PUT | PATCH | DELETE. content_type applies when body
// is non-empty (Kubernetes needs application/merge-patch+json for PATCH).
Response Call(const Config& cfg, const std::string& method,
              const std::string& path, const std::string& body = "",
              const std::string& content_type = "application/json");

// Read a whole file, stripping trailing newlines (token files etc.).
bool ReadFileTrim(const std::string& path, std::string* out);

}  // namespace kubeclient

#endif  // TPU_NATIVE_OPERATOR_KUBECLIENT_H_
