// tpu-metrics-exporter — dcgm-exporter analog (reference README.md:204,213).
//
// Native C++ collector + Prometheus /metrics endpoint (the reference's scrape
// path is native DCGM C++ under a thin exporter; SURVEY.md §2.2 native-parity
// rule). Collectors:
//   - device census: chips discovered from /dev/accel* (or --fake-devices),
//     presence + count against the accelerator type's expectation;
//   - runtime metrics relay: Prometheus-style textfile written by the
//     libtpu/workload side (default /run/tpu/metrics.prom) with per-chip
//     duty-cycle / HBM gauges — the BASELINE config-4 scrape surface;
//   - --status-mode adds the node-status-exporter operand's checks
//     (reference README.md:107): libtpu staged?, plugin socket present?,
//     chip count == expected; served on /status as JSON, /healthz, and as
//     metrics.
//
// HTTP: deliberately minimal HTTP/1.1 (GET only) over a TCP listener; each
// request is answered and closed. Single poll loop, no threads.

#include <arpa/inet.h>
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <sstream>
#include <string>
#include <utility>
#include <vector>

#include "../common/devenum.h"
#include "../common/promescape.h"
#include "../common/promsources.h"
#include "../common/httpread.h"
#include "../plugin/topology.h"

namespace {

volatile sig_atomic_t g_stop = 0;
void HandleSignal(int) { g_stop = 1; }

struct Options {
  int port = 9400;
  std::string device_glob = "/dev/accel*";
  std::string devfs_root;
  std::string accelerator = "v5e-8";
  std::string metrics_file = "/run/tpu/metrics.prom";  // legacy single file
  std::string metrics_dir = "/run/tpu/metrics.d";      // multi-writer drop-dir
  int stale_after_s = 300;   // skip source files older than this
  std::string libtpu_path;   // --status-mode check
  std::string plugin_socket; // --status-mode check
  int expect_chips = -1;     // default: accelerator's chips_per_host
  int fake_devices = -1;
  bool status_mode = false;
  bool once = false;         // print metrics to stdout and exit (tests/CLI)
};

std::vector<std::pair<int, std::string>> DiscoverChips(const Options& opt) {
  std::vector<std::pair<int, std::string>> chips;
  if (opt.fake_devices >= 0) {
    for (int i = 0; i < opt.fake_devices; ++i)
      chips.push_back({i, "/dev/accel" + std::to_string(i)});
    return chips;
  }
  for (const auto& node : devenum::Enumerate(opt.device_glob, opt.devfs_root))
    chips.push_back({node.index, node.path});
  return chips;
}

// Relay validated lines from the runtime-metrics textfiles: only
// tpu_-prefixed metric lines and comments pass through (prevents a hostile
// writer from injecting arbitrary series). Relay size is bounded — the
// writers share the node but not the exporter's memory budget; a runaway
// file must not balloon every scrape response — with the truncation
// surfaced as its own gauge so scrapers can alert instead of silently
// missing series.
//
// Sources are the UNION of the legacy --metrics-file and every *.prom in
// the --metrics-dir drop-dir (node-exporter textfile-collector pattern):
// one file per writer, so two concurrent workloads on a node publish side
// by side instead of clobbering each other. Files older than
// --stale-after are evicted from the relay (a finished Job's gauges must
// not haunt scrapes forever), and a series duplicated across writers
// (e.g. both publish chip 0's HBM) resolves NEWEST-file-wins.
constexpr size_t kRelayLimitBytes = 1 << 20;  // 1 MiB across all sources

struct RelayAccum {
  std::vector<std::string> order;            // key emission order
  std::map<std::string, std::string> lines;  // key -> full line (no \n)
  size_t bytes = 0;
  bool truncated = false;
  int files = 0;
  int stale = 0;
  int dropped = 0;  // sources beyond the promsources cap
};

// End of a sample line's series identity (metric name + label block if
// any): the value starts after it, and anything after the value is an
// OPTIONAL Prometheus timestamp. Splitting at the LAST space (the old
// implementation) misparses a timestamped line both ways: the writer
// label lands after the value (`tpu_x 5{writer="w"} 169…` — invalid
// exposition a strict scraper rejects page-wide) and the dedup key
// absorbs the value, so the same series from two writers never dedups.
size_t SeriesEnd(const std::string& line) {
  size_t brace = line.find('{');
  size_t sp = line.find(' ');
  if (brace == std::string::npos ||
      (sp != std::string::npos && sp < brace)) {
    return sp == std::string::npos ? line.size() : sp;
  }
  // Quote-aware scan for the label block's close: '}' is legal INSIDE a
  // quoted label value (and the drop-dir is hostile-writer territory, see
  // above) — a raw find('}') would truncate the key mid-label and collide
  // distinct series, letting one writer clobber another's.
  bool in_quote = false;
  for (size_t i = brace + 1; i < line.size(); ++i) {
    char c = line[i];
    if (in_quote) {
      if (c == '\\') ++i;  // escaped char inside a quoted value
      else if (c == '"') in_quote = false;
    } else if (c == '"') {
      in_quote = true;
    } else if (c == '}') {
      return i + 1;
    }
  }
  return line.size();
}

void RelayLine(const std::string& raw, const std::string& writer,
               RelayAccum* acc) {
  if (raw.empty()) return;
  if (!(raw[0] == '#' || raw.compare(0, 4, "tpu_") == 0)) return;
  // Unlabeled samples are PROCESS-scoped (tpu_process_devices, the
  // timestamp, tpu_hbm_source): from the multi-writer drop-dir they get a
  // writer label, otherwise two concurrent pods' values would collide on
  // the dedup key and silently reduce to the newest writer's number (and
  // emitting both without labels would be duplicate series — invalid
  // Prometheus). Labeled (per-chip) series stay as-is: chip ids are
  // node-scoped, so newest-wins per chip is the right resolution.
  std::string line = raw;
  if (!writer.empty() && raw[0] != '#' &&
      raw.find('{') == std::string::npos) {
    size_t ne = SeriesEnd(raw);  // end of the bare metric name
    if (ne < raw.size()) {
      line = raw.substr(0, ne) + "{writer=\"" + writer + "\"}" +
             raw.substr(ne);
    }
  }
  // Comments dedup on the whole line (identical HELP/TYPE from several
  // writers emit once); samples dedup on name+labels — never the value
  // or a trailing timestamp — so a later (newer) file's value REPLACES
  // an earlier one for the same series.
  std::string key = line;
  if (line[0] != '#') key = line.substr(0, SeriesEnd(line));
  auto it = acc->lines.find(key);
  if (it != acc->lines.end()) {
    acc->bytes += line.size() - it->second.size();
    it->second = line;
    return;
  }
  if (acc->bytes + line.size() > kRelayLimitBytes) {
    acc->truncated = true;
    return;
  }
  acc->order.push_back(key);
  acc->lines.emplace(std::move(key), line);
  acc->bytes += line.size();
}

void RelayFile(const std::string& file, const std::string& writer,
               RelayAccum* acc) {
  FILE* f = fopen(file.c_str(), "r");
  if (!f) return;
  ++acc->files;
  std::string cur;
  char chunk[1024];
  // Lines are accumulated whole before the filter/emit decision, so a
  // line longer than the chunk buffer is relayed (or dropped) WHOLE — a
  // continuation chunk can neither masquerade as a fresh series nor leave
  // an unterminated fragment — and the truncation break discards any
  // partial line rather than emitting it. Consumption is measured with
  // ftell, not strlen: embedded NUL bytes (crashed writer, sparse file)
  // must not defeat the per-file read bound.
  while (fgets(chunk, sizeof(chunk), f)) {
    cur += chunk;
    long consumed = ftell(f);
    if (consumed < 0 || static_cast<size_t>(consumed) > kRelayLimitBytes) {
      acc->truncated = true;
      break;
    }
    if (!cur.empty() && cur.back() == '\n') {
      cur.pop_back();
      RelayLine(cur, writer, acc);
      cur.clear();
      if (acc->truncated) break;
    }
  }
  // trailing line without a final newline: relay it if it passes
  if (!acc->truncated && !cur.empty()) RelayLine(cur, writer, acc);
  fclose(f);
}

std::string RelayRuntimeMetrics(const Options& opt) {
  // Sources relayed oldest-first so the newest file's duplicates win the
  // per-series dedup (shared discovery with tpu-info — promsources.h;
  // nanosecond mtimes because concurrent writers routinely land in the
  // same second, and a second-granularity tie would hand the win to
  // readdir order).
  RelayAccum acc;
  std::vector<promsources::Source> sources = promsources::Collect(
      opt.metrics_file, opt.metrics_dir, opt.stale_after_s, &acc.stale,
      &acc.dropped);
  for (const auto& src : sources) {
    RelayFile(src.path, src.stem, &acc);
    if (acc.truncated) break;
  }
  if (acc.files == 0 && acc.stale == 0) return "";
  std::string s;
  for (const auto& key : acc.order) s += acc.lines[key] + "\n";
  s += "# HELP tpu_relay_files runtime-metrics source files relayed into "
       "this scrape\n"
       "# TYPE tpu_relay_files gauge\n"
       "tpu_relay_files " + std::to_string(acc.files) + "\n" +
       "# HELP tpu_relay_stale_files source files skipped as stale "
       "(writer gone)\n"
       "# TYPE tpu_relay_stale_files gauge\n"
       "tpu_relay_stale_files " + std::to_string(acc.stale) + "\n";
  // unconditional like the stale gauge: a clean 0 after a flood clears
  // must be distinguishable from the metric not existing
  s += "# HELP tpu_relay_dropped_sources source files beyond the "
       "per-scrape cap (newest kept)\n"
       "# TYPE tpu_relay_dropped_sources gauge\n"
       "tpu_relay_dropped_sources " + std::to_string(acc.dropped) + "\n";
  if (acc.truncated)
    s += "# HELP tpu_relay_truncated runtime-metrics relay exceeded its "
         "limit; series beyond it were dropped\n"
         "# TYPE tpu_relay_truncated gauge\n"
         "tpu_relay_truncated 1\n";
  return s;
}

struct StatusChecks {
  bool libtpu_ok = true;
  bool plugin_socket_ok = true;
  bool chip_count_ok = true;
  size_t chips = 0;
  int expected = 0;
  bool healthy() const {
    return libtpu_ok && plugin_socket_ok && chip_count_ok;
  }
};

StatusChecks RunChecks(const Options& opt, const tpud::AcceleratorType* acc) {
  StatusChecks st;
  auto chips = DiscoverChips(opt);
  st.chips = chips.size();
  st.expected =
      opt.expect_chips >= 0 ? opt.expect_chips : (acc ? acc->chips_per_host : 0);
  st.chip_count_ok = static_cast<int>(st.chips) == st.expected;
  if (!opt.libtpu_path.empty()) {
    std::string p = opt.libtpu_path;
    if (!opt.devfs_root.empty()) p = opt.devfs_root + p;
    st.libtpu_ok = access(p.c_str(), R_OK) == 0;
  }
  if (!opt.plugin_socket.empty()) {
    std::string p = opt.plugin_socket;
    if (!opt.devfs_root.empty()) p = opt.devfs_root + p;
    struct stat sb;
    st.plugin_socket_ok =
        stat(p.c_str(), &sb) == 0 && S_ISSOCK(sb.st_mode);
  }
  return st;
}

std::string RenderMetrics(const Options& opt,
                          const tpud::AcceleratorType* acc) {
  std::ostringstream os;
  auto chips = DiscoverChips(opt);
  os << "# HELP tpu_chips_total TPU chips discovered on this node\n"
     << "# TYPE tpu_chips_total gauge\n"
     << "tpu_chips_total " << chips.size() << "\n";
  int expected =
      opt.expect_chips >= 0 ? opt.expect_chips : (acc ? acc->chips_per_host : 0);
  os << "# HELP tpu_chips_expected chips expected for the accelerator type\n"
     << "# TYPE tpu_chips_expected gauge\n"
     << "tpu_chips_expected " << expected << "\n";
  os << "# HELP tpu_chip_present device node present (per chip)\n"
     << "# TYPE tpu_chip_present gauge\n";
  for (const auto& [idx, path] : chips)
    // the path label is filesystem-controlled bytes: escape per the
    // exposition format (promescape.h, the MetricsRegistry.render twin)
    // so a hostile device-dir entry cannot forge extra samples
    os << "tpu_chip_present{chip=\"" << idx << "\",path=\""
       << promescape::EscapeLabelValue(path) << "\"} 1\n";
  if (acc) {
    os << "# HELP tpu_hbm_capacity_bytes HBM capacity per chip\n"
       << "# TYPE tpu_hbm_capacity_bytes gauge\n";
    for (const auto& [idx, path] : chips)
      os << "tpu_hbm_capacity_bytes{chip=\"" << idx << "\"} "
         << (int64_t(acc->hbm_gib_per_chip) << 30) << "\n";
  }
  os << RelayRuntimeMetrics(opt);
  if (opt.status_mode) {
    StatusChecks st = RunChecks(opt, acc);
    os << "# HELP tpu_stack_check TPU stack health checks (1 = ok)\n"
       << "# TYPE tpu_stack_check gauge\n"
       << "tpu_stack_check{check=\"libtpu_staged\"} " << st.libtpu_ok << "\n"
       << "tpu_stack_check{check=\"plugin_socket\"} " << st.plugin_socket_ok
       << "\n"
       << "tpu_stack_check{check=\"chip_count\"} " << st.chip_count_ok << "\n"
       << "tpu_stack_healthy " << st.healthy() << "\n";
  }
  return os.str();
}

std::string RenderStatusJson(const Options& opt,
                             const tpud::AcceleratorType* acc) {
  StatusChecks st = RunChecks(opt, acc);
  std::ostringstream os;
  os << "{\"healthy\": " << (st.healthy() ? "true" : "false")
     << ", \"chips\": " << st.chips << ", \"expected_chips\": " << st.expected
     << ", \"checks\": {\"libtpu_staged\": " << (st.libtpu_ok ? "true" : "false")
     << ", \"plugin_socket\": " << (st.plugin_socket_ok ? "true" : "false")
     << ", \"chip_count\": " << (st.chip_count_ok ? "true" : "false")
     << "}}\n";
  return os.str();
}

void HttpRespond(int fd, int code, const char* ctype,
                 const std::string& body) {
  std::ostringstream os;
  os << "HTTP/1.1 " << code << (code == 200 ? " OK" : " Service Unavailable")
     << "\r\nContent-Type: " << ctype
     << "\r\nContent-Length: " << body.size()
     << "\r\nConnection: close\r\n\r\n"
     << body;
  std::string out = os.str();
  size_t off = 0;
  while (off < out.size()) {
    ssize_t n = write(fd, out.data() + off, out.size() - off);
    if (n <= 0) break;
    off += n;
  }
}

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto val = [&](const char* k) -> const char* {
      size_t n = strlen(k);
      if (a.compare(0, n, k) == 0 && a[n] == '=') return a.c_str() + n + 1;
      return nullptr;
    };
    const char* v;
    if ((v = val("--port"))) opt.port = atoi(v);
    else if ((v = val("--device-glob"))) opt.device_glob = v;
    else if ((v = val("--devfs-root"))) opt.devfs_root = v;
    else if ((v = val("--accelerator"))) opt.accelerator = v;
    else if ((v = val("--metrics-file"))) opt.metrics_file = v;
    else if ((v = val("--metrics-dir"))) opt.metrics_dir = v;
    else if ((v = val("--stale-after"))) opt.stale_after_s = atoi(v);
    else if ((v = val("--libtpu-path"))) opt.libtpu_path = v;
    else if ((v = val("--plugin-socket"))) opt.plugin_socket = v;
    else if ((v = val("--expect-chips"))) opt.expect_chips = atoi(v);
    else if ((v = val("--fake-devices"))) opt.fake_devices = atoi(v);
    else if (a == "--status-mode") opt.status_mode = true;
    else if (a == "--once") opt.once = true;
    else {
      fprintf(stderr,
              "usage: tpu-metrics-exporter [--port=9400] [--device-glob=G]\n"
              "  [--devfs-root=D] [--accelerator=T] [--metrics-file=F]\n"
              "  [--metrics-dir=D] [--stale-after=SECONDS]\n"
              "  [--status-mode --libtpu-path=P --plugin-socket=S\n"
              "   --expect-chips=N] [--fake-devices=N] [--once]\n");
      return 2;
    }
  }

  const tpud::AcceleratorType* acc = tpud::FindAccelerator(opt.accelerator);

  if (opt.once) {
    printf("%s", RenderMetrics(opt, acc).c_str());
    return 0;
  }

  signal(SIGINT, HandleSignal);
  signal(SIGTERM, HandleSignal);
  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) { perror("socket"); return 1; }
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(opt.port));
  if (bind(lfd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(lfd, 16) != 0) { perror("listen"); return 1; }
  fprintf(stderr, "tpu-metrics-exporter: listening on :%d%s\n", opt.port,
          opt.status_mode ? " (status mode)" : "");

  while (!g_stop) {
    struct pollfd pfd = {lfd, POLLIN, 0};
    int rc = poll(&pfd, 1, 500);
    if (rc <= 0) continue;
    int cfd = accept(lfd, nullptr, nullptr);
    if (cfd < 0) continue;
    // A silent or stuck client must not wedge the single-threaded daemon:
    // bound both directions of the exchange (same guard as the operator's
    // status server).
    struct timeval tv = {0, 500 * 1000};
    setsockopt(cfd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(cfd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    // A client may legitimately split the head across TCP segments; the
    // shared reader loops until \r\n\r\n under a wall-clock deadline
    // (native/common/httpread.h).
    char buf[8192];
    size_t have = httpread::ReadRequestHead(cfd, buf, sizeof(buf), &g_stop);
    if (have > 0) {
      char method[8], path[256];
      if (sscanf(buf, "%7s %255s", method, path) == 2 &&
          strcmp(method, "GET") == 0) {
        if (strcmp(path, "/metrics") == 0) {
          HttpRespond(cfd, 200, "text/plain; version=0.0.4",
                      RenderMetrics(opt, acc));
        } else if (strcmp(path, "/healthz") == 0) {
          StatusChecks st = RunChecks(opt, acc);
          bool ok = opt.status_mode ? st.healthy() : true;
          HttpRespond(cfd, ok ? 200 : 503, "text/plain",
                      ok ? "ok\n" : "unhealthy\n");
        } else if (strcmp(path, "/status") == 0) {
          HttpRespond(cfd, 200, "application/json",
                      RenderStatusJson(opt, acc));
        } else {
          HttpRespond(cfd, 200, "text/plain",
                      "tpu-metrics-exporter: /metrics /healthz /status\n");
        }
      }
    }
    close(cfd);
  }
  close(lfd);
  return 0;
}
