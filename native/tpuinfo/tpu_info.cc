// tpu-info — host-level TPU probe, the nvidia-smi analog.
//
// The reference's acceptance check execs nvidia-smi in the driver pod and
// compares a pasted table (reference README.md:152-168). tpu-info is the TPU
// stack's equivalent native probe: it enumerates the TPU device nodes, reads
// what the host exposes (sysfs NUMA node, optional runtime-metrics textfile
// written by the workload/libtpu side), and prints a table, one line
// (--oneline, used by the libtpu-prep readiness probe), or JSON (--json).
//
// Runtime metrics interface: Prometheus-style textfiles with lines like
//   tpu_duty_cycle_percent{chip="0"} 37.5
//   tpu_hbm_used_bytes{chip="0"} 1073741824
// Workloads publish per-writer files into the /run/tpu/metrics.d drop-dir
// (legacy single /run/tpu/metrics.prom also read); non-stale files merge
// oldest-first so the newest writer's value wins per chip — the same
// union the tpu-metrics-exporter relays; see docs/DELTAS.md §5.

#include <dirent.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <string>
#include <vector>

#include "../common/devenum.h"
#include "../common/promsources.h"
#include "../plugin/topology.h"

namespace {

struct Chip {
  int index;
  std::string path;
  bool present;
  int numa = -1;
  double duty_cycle = -1;   // percent; -1 = unknown
  double tc_util = -1;      // tensorcore utilization percent
  double hbm_used = -1;     // bytes
};

int ReadNuma(const std::string& dev_path) {
  const char* base = strrchr(dev_path.c_str(), '/');
  if (!base) return -1;
  std::string sysfs =
      "/sys/class/accel/" + std::string(base + 1) + "/device/numa_node";
  FILE* f = fopen(sysfs.c_str(), "r");
  if (!f) return -1;
  int node = -1;
  if (fscanf(f, "%d", &node) != 1) node = -1;
  fclose(f);
  return node;
}

std::vector<Chip> Discover(const std::string& device_glob,
                           const std::string& devfs_root, int fake) {
  std::vector<Chip> chips;
  if (fake >= 0) {
    for (int i = 0; i < fake; ++i)
      chips.push_back({i, "/dev/accel" + std::to_string(i), true});
    return chips;
  }
  for (const auto& node : devenum::Enumerate(device_glob, devfs_root))
    chips.push_back({node.index, node.path,
                     access(node.path.c_str(), F_OK) == 0, ReadNuma(node.path)});
  return chips;
}

// Parses `name{chip="N"} value` lines for the metrics we display.
void MergeRuntimeMetrics(const std::string& file, std::vector<Chip>* chips) {
  FILE* f = fopen(file.c_str(), "r");
  if (!f) return;
  char line[512];
  while (fgets(line, sizeof(line), f)) {
    if (line[0] == '#') continue;
    char name[128], labels[256];
    double value;
    if (sscanf(line, "%127[a-zA-Z0-9_]{%255[^}]} %lf", name, labels, &value) !=
        3)
      continue;
    int chip = -1;
    const char* c = strstr(labels, "chip=\"");
    if (c) chip = atoi(c + 6);
    for (auto& ch : *chips) {
      if (ch.index != chip) continue;
      if (strcmp(name, "tpu_duty_cycle_percent") == 0) ch.duty_cycle = value;
      if (strcmp(name, "tpu_tensorcore_utilization_percent") == 0)
        ch.tc_util = value;
      if (strcmp(name, "tpu_hbm_used_bytes") == 0) ch.hbm_used = value;
    }
  }
  fclose(f);
}

// Merge the legacy file plus every non-stale *.prom in the drop-dir,
// oldest-first so the NEWEST writer's value wins per chip — the same
// union/eviction/ordering as the exporter's relay, via the SHARED source
// discovery (native/common/promsources.h).
void MergeAllRuntimeMetrics(const std::string& file, const std::string& dir,
                            int stale_after_s, std::vector<Chip>* chips) {
  for (const auto& src :
       promsources::Collect(file, dir, stale_after_s, nullptr))
    MergeRuntimeMetrics(src.path, chips);
}

}  // namespace

int main(int argc, char** argv) {
  std::string device_glob = "/dev/accel*";
  std::string devfs_root;
  std::string accelerator = "v5e-8";
  std::string metrics_file = "/run/tpu/metrics.prom";
  std::string metrics_dir = "/run/tpu/metrics.d";
  int stale_after_s = 300;
  int fake = -1;
  bool json = false, oneline = false;

  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto val = [&](const char* k) -> const char* {
      size_t n = strlen(k);
      if (a.compare(0, n, k) == 0 && a[n] == '=') return a.c_str() + n + 1;
      return nullptr;
    };
    const char* v;
    if ((v = val("--device-glob"))) device_glob = v;
    else if ((v = val("--devfs-root"))) devfs_root = v;
    else if ((v = val("--accelerator"))) accelerator = v;
    else if ((v = val("--metrics-file"))) metrics_file = v;
    else if ((v = val("--metrics-dir"))) metrics_dir = v;
    else if ((v = val("--stale-after"))) stale_after_s = atoi(v);
    else if ((v = val("--fake-devices"))) fake = atoi(v);
    else if (a == "--json") json = true;
    else if (a == "--oneline") oneline = true;
    else {
      fprintf(stderr,
              "usage: tpu-info [--device-glob=G] [--devfs-root=D] "
              "[--accelerator=T] [--metrics-file=F] [--metrics-dir=D] "
              "[--stale-after=S] [--fake-devices=N] "
              "[--json|--oneline]\n");
      return 2;
    }
  }

  const tpud::AcceleratorType* acc = tpud::FindAccelerator(accelerator);
  auto chips = Discover(device_glob, devfs_root, fake);
  MergeAllRuntimeMetrics(metrics_file, metrics_dir, stale_after_s, &chips);

  if (oneline) {
    printf("tpu-info: %zu chip(s) [%s %s]\n", chips.size(),
           acc ? acc->name.c_str() : accelerator.c_str(),
           acc ? acc->LabelTopology().c_str() : "?");
    return chips.empty() ? 1 : 0;
  }

  if (json) {
    printf("{\"accelerator\": \"%s\", \"topology\": \"%s\", \"chips\": [",
           acc ? acc->name.c_str() : accelerator.c_str(),
           acc ? acc->LabelTopology().c_str() : "");
    for (size_t i = 0; i < chips.size(); ++i) {
      const Chip& c = chips[i];
      printf("%s{\"index\": %d, \"path\": \"%s\", \"present\": %s, "
             "\"numa\": %d",
             i ? ", " : "", c.index, c.path.c_str(),
             c.present ? "true" : "false", c.numa);
      if (c.duty_cycle >= 0) printf(", \"duty_cycle_percent\": %g",
                                    c.duty_cycle);
      if (c.tc_util >= 0)
        printf(", \"tensorcore_utilization_percent\": %g", c.tc_util);
      if (c.hbm_used >= 0) printf(", \"hbm_used_bytes\": %.0f", c.hbm_used);
      printf("}");
    }
    // The duty-cycle producer is one measurement per OWNING PROCESS,
    // attributed to every chip that process holds (libtpu exposes no
    // per-chip counter daemon to ask) — scope declared so a reader can't
    // mistake identical per-chip values for independent measurements
    // (docs/DELTAS.md §5).
    printf("], \"chip_count\": %zu, \"duty_cycle_scope\": \"process\"}\n",
           chips.size());
    return chips.empty() ? 1 : 0;
  }

  // Table mode — the human-facing nvidia-smi analog. duty%/tc% are
  // trailing-window, process-scoped rates (docs/DELTAS.md §5).
  printf("+-----------------------------------------------------------------------+\n");
  printf("| tpu-info          accelerator: %-8s  topology: %-6s             |\n",
         acc ? acc->name.c_str() : accelerator.c_str(),
         acc ? acc->LabelTopology().c_str() : "?");
  printf("|-----------------------------------------------------------------------|\n");
  printf("| chip | device        | present | numa | duty%% |  tc%%  | HBM used      |\n");
  printf("|------+---------------+---------+------+-------+-------+---------------|\n");
  for (const Chip& c : chips) {
    char duty[16] = "   - ", tc[16] = "   - ", hbm[24] = "      -      ";
    if (c.duty_cycle >= 0) snprintf(duty, sizeof(duty), "%5.1f", c.duty_cycle);
    if (c.tc_util >= 0) snprintf(tc, sizeof(tc), "%5.1f", c.tc_util);
    if (c.hbm_used >= 0)
      snprintf(hbm, sizeof(hbm), "%9.0f MiB", c.hbm_used / (1024.0 * 1024));
    printf("| %4d | %-13s | %-7s | %4d | %s | %s | %s |\n", c.index,
           c.path.c_str(), c.present ? "yes" : "no", c.numa, duty, tc, hbm);
  }
  if (chips.empty())
    printf("|      no TPU device nodes found (%-36s) |\n",
           device_glob.c_str());
  printf("+-----------------------------------------------------------------------+\n");
  return chips.empty() ? 1 : 0;
}
