// tpu-feature-discovery — native label publisher (gpu-feature-discovery
// analog, reference README.md:108,209).
//
// The reference's feature discovery is a Go daemon (NFD sidecar) that labels
// accelerator nodes so the operator and workloads can target them
// (reference README.md:119). This C++ daemon reproduces that for TPU nodes:
//
//  - discovers chips from the host device tree (/dev/accel* or /dev/vfio/*,
//    re-rootable via --devfs-root for the fake-device-tree test story,
//    SURVEY.md §4 point 2);
//  - computes the label set (present/type/generation/topology/count/
//    ici-domain) and PATCHes it onto this Node via the Kubernetes API;
//  - with --conditions also publishes a TpuReady Node condition
//    (node-problem-detector style) from the chip census on the status
//    subresource;
//  - clusterless modes for tests: --print emits the record as JSON,
//    --out-file appends it (the fake-apiserver story).
//
// The label/condition *semantics* are pinned to the Python oracle
// (tpu_cluster/discovery/labels.py + labeler.py): tests/test_discovery.py
// runs both against the same fake device tree and diffs the JSON records
// byte-for-byte (timestamps normalized), so the two implementations cannot
// drift. JSON output therefore matches Python's
// json.dumps(..., sort_keys=True) formatting exactly.
//
// Unlike the Python stand-in it replaces, apiserver errors back off
// exponentially with jitter (fleet-safe at large node counts) and the
// daemon can target an explicit --apiserver (fake apiserver in tests) in
// addition to the in-cluster ServiceAccount config.

#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <optional>
#include <string>
#include <vector>

#include "../common/devenum.h"
#include "../operator/kubeclient.h"
#include "../plugin/topology.h"

namespace {

volatile sig_atomic_t g_stop = 0;
void HandleSignal(int) { g_stop = 1; }

struct Options {
  std::string accelerator = "v5e-8";
  std::string device_glob = "/dev/accel*";
  std::string devfs_root;
  int fake_devices = -1;  // >=0: synthesize N chips (kind e2e; mirrors tpud)
  double interval_s = 60;
  bool conditions = false;
  bool oneshot = false;
  bool print_only = false;
  std::string out_file;
  // apiserver access (tests); empty apiserver = in-cluster config
  std::string apiserver;
  std::string token_file;
  std::string ca_file;
  bool insecure_skip_tls_verify = false;
};

// ------------------------------------------------------------ labels

// Ordered map with optional values; nullopt serialises to JSON null, which
// deletes the key in a strategic-merge patch (stale-label cleanup, see
// labels.py compute_labels docstring).
using LabelMap = std::map<std::string, std::optional<std::string>>;

LabelMap ComputeLabels(const tpud::AcceleratorType& acc, int count,
                       const std::string& node_name) {
  LabelMap out;
  if (count == 0) {
    out["google.com/tpu.present"] = std::string("false");
    out["google.com/tpu.accelerator-type"] = std::nullopt;
    out["google.com/tpu.generation"] = std::nullopt;
    out["google.com/tpu.topology"] = std::nullopt;
    out["google.com/tpu.count"] = std::nullopt;
    out["google.com/tpu.ici-domain"] = std::nullopt;
    return out;
  }
  out["google.com/tpu.present"] = std::string("true");
  out["google.com/tpu.accelerator-type"] = acc.name;
  out["google.com/tpu.generation"] = acc.generation;
  out["google.com/tpu.topology"] = acc.LabelTopology();
  out["google.com/tpu.count"] = std::to_string(count);
  out["google.com/tpu.ici-domain"] =
      node_name.empty() ? std::string("local") : node_name;
  return out;
}

struct Condition {
  std::string status, reason, message;
  std::string heartbeat, transition;  // empty = omit (matches Python now="")
};

Condition TpuReadyCondition(const tpud::AcceleratorType& acc, int found,
                            const std::string& now,
                            const Condition* previous) {
  Condition c;
  int expected = acc.chips_per_host;
  char msg[128];
  if (found == expected) {
    c.status = "True";
    c.reason = "AllChipsPresent";
    snprintf(msg, sizeof(msg), "%d/%d TPU chips present", found, expected);
  } else if (found == 0) {
    c.status = "False";
    c.reason = "NoTpuDevices";
    snprintf(msg, sizeof(msg), "no TPU device nodes (expected %d)", expected);
  } else {
    c.status = "False";
    c.reason = "DegradedChipSet";
    snprintf(msg, sizeof(msg), "%d/%d TPU chips present", found, expected);
  }
  c.message = msg;
  if (!now.empty()) {
    c.heartbeat = now;
    // Preserve lastTransitionTime across heartbeats when status unchanged
    // (kubelet-condition semantics; see labeler.tpu_ready_condition).
    if (previous && previous->status == c.status &&
        !previous->transition.empty())
      c.transition = previous->transition;
    else
      c.transition = now;
  }
  return c;
}

// ------------------------------------------------------------ JSON emit
// Matches Python json.dumps(..., sort_keys=True): ", " and ": " separators,
// keys sorted at every level. Our strings are plain ASCII label/reason text
// so escaping is limited to the JSON-mandatory set.

void AppendJsonString(std::string* out, const std::string& s) {
  out->push_back('"');
  for (char ch : s) {
    switch (ch) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\t': *out += "\\t"; break;
      case '\r': *out += "\\r"; break;
      default: out->push_back(ch);
    }
  }
  out->push_back('"');
}

std::string LabelsJson(const LabelMap& labels) {
  std::string out = "{";
  bool first = true;
  for (const auto& [k, v] : labels) {  // std::map iterates sorted
    if (!first) out += ", ";
    first = false;
    AppendJsonString(&out, k);
    out += ": ";
    if (v)
      AppendJsonString(&out, *v);
    else
      out += "null";
  }
  out += "}";
  return out;
}

std::string ConditionJson(const Condition& c) {
  // Sorted keys: lastHeartbeatTime, lastTransitionTime, message, reason,
  // status, type.
  std::string out = "{";
  bool first = true;
  auto emit = [&](const char* key, const std::string& val) {
    if (val.empty()) return;
    if (!first) out += ", ";
    first = false;
    AppendJsonString(&out, key);
    out += ": ";
    AppendJsonString(&out, val);
  };
  emit("lastHeartbeatTime", c.heartbeat);
  emit("lastTransitionTime", c.transition);
  emit("message", c.message);
  emit("reason", c.reason);
  emit("status", c.status);
  out += first ? "\"type\": \"TpuReady\"}" : ", \"type\": \"TpuReady\"}";
  return out;
}

std::string RecordJson(const LabelMap& labels, const Condition* cond) {
  // Sorted record keys: "condition" < "labels".
  std::string out = "{";
  if (cond) {
    out += "\"condition\": " + ConditionJson(*cond) + ", ";
  }
  out += "\"labels\": " + LabelsJson(labels) + "}";
  return out;
}

std::string NodePatch(const LabelMap& labels) {
  return "{\"metadata\": {\"labels\": " + LabelsJson(labels) + "}}";
}

std::string StatusPatch(const Condition& c) {
  return "{\"status\": {\"conditions\": [" + ConditionJson(c) + "]}}";
}

std::string NowUtc() {
  char buf[32];
  time_t t = time(nullptr);
  struct tm tm_utc;
  gmtime_r(&t, &tm_utc);
  strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%SZ", &tm_utc);
  return buf;
}

// ------------------------------------------------------------ publish

bool PatchNode(const kubeclient::Config& cfg, const std::string& node,
               const std::string& patch, bool status_subresource,
               std::string* err) {
  std::string path = "/api/v1/nodes/" + node;
  if (status_subresource) path += "/status";
  kubeclient::Response r =
      kubeclient::Call(cfg, "PATCH", path, patch,
                       "application/strategic-merge-patch+json");
  if (!r.ok()) {
    *err = "PATCH " + path + " -> " + std::to_string(r.status) + " " +
           (r.status ? r.body.substr(0, 160) : r.error);
    return false;
  }
  return true;
}

// One discovery+publish cycle; mirrors labeler.run_once. Returns false only
// on publish failure (print/out-file modes cannot fail discovery).
bool RunOnce(const Options& opt, const tpud::AcceleratorType& acc,
             const kubeclient::Config& cfg, const std::string& node_name,
             std::optional<Condition>* previous, std::string* err) {
  std::vector<devenum::Node> found;
  if (opt.fake_devices >= 0) {
    for (int i = 0; i < opt.fake_devices; ++i)
      found.push_back({i, "/dev/accel" + std::to_string(i)});
  } else {
    found = devenum::Enumerate(opt.device_glob, opt.devfs_root);
    if (found.empty())  // VFIO fallback, like devices.discover_vfio
      found = devenum::Enumerate("/dev/vfio/*", opt.devfs_root);
  }
  LabelMap labels =
      ComputeLabels(acc, static_cast<int>(found.size()), node_name);
  std::optional<Condition> cond;
  if (opt.conditions) {
    const Condition* prev = previous->has_value() ? &**previous : nullptr;
    cond = TpuReadyCondition(acc, static_cast<int>(found.size()), NowUtc(),
                             prev);
  }
  std::string record = RecordJson(labels, cond ? &*cond : nullptr);
  if (opt.print_only) {
    printf("%s\n", record.c_str());
  } else if (!opt.out_file.empty()) {
    FILE* f = fopen(opt.out_file.c_str(), "a");
    if (!f) {
      *err = "cannot open " + opt.out_file;
      return false;
    }
    fprintf(f, "%s\n", record.c_str());
    fclose(f);
  } else {
    if (!PatchNode(cfg, node_name, NodePatch(labels), false, err))
      return false;
    fprintf(stderr, "patched node %s labels\n", node_name.c_str());
    if (cond) {
      if (!PatchNode(cfg, node_name, StatusPatch(*cond), true, err))
        return false;
      fprintf(stderr, "patched node %s condition TpuReady=%s\n",
              node_name.c_str(), cond->status.c_str());
    }
  }
  *previous = cond;
  return true;
}

// Sleep interval with ±10% jitter (de-synchronises the fleet's apiserver
// load), doubling after consecutive failures. The cap bounds only the
// failure backoff — and is max(5 min, interval) so a configured --interval
// above 300s is honored, mirroring the Python oracle (labeler.py).
void JitteredSleep(double base_s, int failures) {
  double backoff = base_s;
  double cap = base_s > 300 ? base_s : 300;
  for (int i = 0; i < failures && backoff < cap; ++i) backoff *= 2;
  if (failures > 0 && backoff > cap) backoff = cap;
  double jitter = 0.9 + 0.2 * (static_cast<double>(rand()) / RAND_MAX);
  int total_ms = static_cast<int>(backoff * jitter * 1000);
  for (int left = total_ms; left > 0 && !g_stop; left -= 50)
    usleep(std::min(left, 50) * 1000);
}

bool FlagVal(const char* arg, const char* name, std::string* out) {
  size_t n = strlen(name);
  if (strncmp(arg, name, n) == 0 && arg[n] == '=') {
    *out = arg + n + 1;
    return true;
  }
  return false;
}

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  std::string sval;
  for (int i = 1; i < argc; ++i) {
    const char* a = argv[i];
    if (FlagVal(a, "--accelerator", &opt.accelerator)) continue;
    if (FlagVal(a, "--device-glob", &opt.device_glob)) continue;
    if (FlagVal(a, "--devfs-root", &opt.devfs_root)) continue;
    if (FlagVal(a, "--fake-devices", &sval)) { opt.fake_devices = atoi(sval.c_str()); continue; }
    if (FlagVal(a, "--interval", &sval)) {
      char* end = nullptr;
      opt.interval_s = strtod(sval.c_str(), &end);
      // Garbage or non-positive intervals must fail loudly (argparse-style,
      // like the Python oracle), not turn into a zero-delay apiserver
      // hot loop across the fleet.
      if (end == sval.c_str() || *end != '\0' || opt.interval_s <= 0) {
        fprintf(stderr, "tpu-tfd: invalid --interval=%s\n", sval.c_str());
        return 2;
      }
      continue;
    }
    if (FlagVal(a, "--out-file", &opt.out_file)) continue;
    if (FlagVal(a, "--apiserver", &opt.apiserver)) continue;
    if (FlagVal(a, "--token-file", &opt.token_file)) continue;
    if (FlagVal(a, "--ca-file", &opt.ca_file)) continue;
    if (strcmp(a, "--conditions") == 0) { opt.conditions = true; continue; }
    if (strcmp(a, "--oneshot") == 0) { opt.oneshot = true; continue; }
    if (strcmp(a, "--print") == 0) { opt.print_only = true; continue; }
    if (strcmp(a, "--insecure-skip-tls-verify") == 0) {
      opt.insecure_skip_tls_verify = true;
      continue;
    }
    fprintf(stderr,
            "tpu-tfd: unknown flag %s\n"
            "usage: tpu-tfd [--accelerator=T] [--device-glob=G] "
            "[--devfs-root=D] [--fake-devices=N]\n"
            "  [--interval=SECS] [--conditions] [--oneshot] [--print] "
            "[--out-file=F]\n"
            "  [--apiserver=URL] [--token-file=F] [--ca-file=F] "
            "[--insecure-skip-tls-verify]\n",
            a);
    return 2;
  }

  // Permanent configuration errors must crash the pod (CrashLoopBackOff is
  // the operator-visible signal), not retry forever looking healthy.
  const tpud::AcceleratorType* acc = tpud::FindAccelerator(opt.accelerator);
  if (!acc) {
    std::string known;
    for (const auto& n : tpud::KnownAccelerators())
      known += (known.empty() ? "" : ", ") + n;
    fprintf(stderr, "fatal: unknown accelerator type '%s'; known: %s\n",
            opt.accelerator.c_str(), known.c_str());
    return 2;
  }

  const char* node_env = getenv("NODE_NAME");
  std::string node_name = node_env ? node_env : "";
  bool clusterless = opt.print_only || !opt.out_file.empty();
  if (!clusterless && node_name.empty()) {
    fprintf(stderr,
            "fatal: NODE_NAME env not set (downward-API fieldRef missing "
            "from the DaemonSet manifest?)\n");
    return 2;
  }

  kubeclient::Config cfg;
  if (!clusterless) {
    if (!opt.apiserver.empty()) {
      cfg.base_url = opt.apiserver;
      if (!opt.token_file.empty())
        kubeclient::ReadFileTrim(opt.token_file, &cfg.token);
      cfg.ca_file = opt.ca_file;
    } else if (!kubeclient::Config::InCluster(&cfg)) {
      fprintf(stderr, "fatal: not in-cluster and no --apiserver given\n");
      return 2;
    }
    cfg.insecure_skip_tls_verify = opt.insecure_skip_tls_verify;
  }

  signal(SIGINT, HandleSignal);
  signal(SIGTERM, HandleSignal);
  signal(SIGPIPE, SIG_IGN);
  srand(static_cast<unsigned>(getpid() ^ time(nullptr)));

  std::optional<Condition> previous;
  int failures = 0;
  while (!g_stop) {
    std::string err;
    if (RunOnce(opt, *acc, cfg, node_name, &previous, &err)) {
      failures = 0;
    } else {
      if (opt.oneshot) {
        fprintf(stderr, "tpu-tfd: %s\n", err.c_str());
        return 1;
      }
      ++failures;
      fprintf(stderr, "label refresh failed (will retry): %s\n",
              err.c_str());
    }
    if (opt.oneshot) return 0;
    JitteredSleep(opt.interval_s, failures);
  }
  return 0;
}
